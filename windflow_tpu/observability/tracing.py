"""Per-batch causal tracing + flight recorder — the other half of the
reference's tracing story.

Upstream WindFlow pairs its ``TRACE_WINDFLOW`` counters with external profiler
captures (SURVEY §5); PR 1 reproduced the counter half (``Stats_Record``,
``MetricsRegistry``).  This module adds the *causal* half: which batch hit the
p99, and where its time went — queue wait vs service vs governor throttle vs
supervised restart — as it crossed operator chains, SPSC rings, and restores.

Three pieces:

- **Deterministic trace ids** minted at ingest from ``(run_id, stream,
  position)`` — the :class:`~windflow_tpu.control.admission.PositionBucket`
  convention: a pure function of stream position, so a supervised replay
  after a restore re-mints *identical* ids for the replayed batches and
  exemplars/flows stay stable across recovery.  The id rides on the batch as
  host-side sidecar metadata (``batch.py::TRACE_META_ATTR`` — never a pytree
  field, so compiled programs and cached executables are untouched).
- **Flight recorder**: a bounded, pre-allocated ring buffer of stage records
  (ingest / ring enqueue / ring dequeue / service begin+end), one segment per
  thread so the hot path never takes a lock — a writer owns its segment; the
  only locked operation is segment *registration* (once per thread) and the
  final dump.  Oldest records are overwritten when a segment wraps (it is a
  flight recorder: the recent past survives a crash).
- **Exporters**: :func:`to_chrome_trace` renders the records (plus the event
  journal, when monitoring ran too) as Chrome trace-event JSON — Perfetto-
  loadable, one track per stage plus ring-edge residency slices and flow
  arrows, so it can sit beside an ``xprof_trace`` capture;
  :func:`critical_path_report` prints the per-stage critical-path breakdown
  and a drill-down of the slowest batches (``scripts/wf_trace.py`` is the
  CLI over both).

Everything is **off by default** and follows the ``monitoring=`` / ``faults=``
/ ``control=`` convention: ``trace=`` kwarg on every driver, or process-wide::

    WF_TRACE=1                 # defaults: ./wf_trace output directory
    WF_TRACE=/path/out         # same, custom output directory
    WF_TRACE_SAMPLE=16         # trace every 16th offered batch (default 1)

With tracing off, every runtime call site costs one module-attribute load +
``None`` check (the ``journal.record`` pattern).  Sampling is *positional*
(``pos % sample_every``), never wall-clock, so the traced subset is itself
replay-deterministic.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Union

from . import journal as _journal

#: host-side sidecar attribute carrying the trace id on a Batch — the SAME
#: name as ``windflow_tpu.batch.TRACE_META_ATTR`` (documented there); kept as
#: a literal so this module stays importable without JAX.
TRACE_META_ATTR = "_wf_trace"

#: record kinds (flight-recorder rows and the flight.jsonl schema)
K_INGEST = "ingest"        # trace id minted at the source boundary
K_ENQ = "enq"              # batch pushed into an SPSC ring edge
K_DEQ = "deq"              # batch popped from an SPSC ring edge
K_BEGIN = "begin"          # stage service span opened
K_END = "end"              # stage service span closed (extra: aborted=reason)


@dataclasses.dataclass
class TraceConfig:
    """Resolved tracing settings for one driver run."""

    out_dir: str = "wf_trace"
    #: trace every Nth *offered* batch (positional — replay-deterministic);
    #: 1 = every batch
    sample_every: int = 1
    #: flight-recorder ring capacity, records per thread segment
    ring_capacity: int = 8192
    #: trace-id namespace; None = the driver's name. Make it explicit when
    #: comparing runs (same run_id + same positions => byte-identical ids).
    run_id: Optional[str] = None
    #: id minting mode: ``"position"`` derives ids from (run_id, stream,
    #: offered position) — replay-stable, REQUIRED under supervision;
    #: ``"sequence"`` uses a process-global counter (live-only: a replay
    #: after restore would mint fresh ids and orphan every exemplar).
    ids: str = "position"

    def __post_init__(self):
        if self.ids not in ("position", "sequence"):
            raise ValueError(f"unknown trace id mode {self.ids!r} "
                             f"(modes: position, sequence)")
        if int(self.sample_every) < 1:
            raise ValueError(f"trace sample_every must be >= 1, got "
                             f"{self.sample_every}")
        if int(self.ring_capacity) < 1:
            raise ValueError(f"trace ring_capacity must be >= 1, got "
                             f"{self.ring_capacity}")

    @classmethod
    def resolve(cls, trace: Union[None, bool, str, "TraceConfig"],
                ) -> Optional["TraceConfig"]:
        """Normalize the user-facing ``trace=`` argument (the
        ``MonitoringConfig.resolve`` convention).  ``None`` consults
        ``WF_TRACE`` (``''``/``'0'`` = off); ``False`` forces off; ``True``
        = defaults; a string is the output directory; a config passes
        through.  ``WF_TRACE_SAMPLE`` overrides ``sample_every`` either way.
        Returns None when tracing is off."""
        if trace is False:
            return None
        if isinstance(trace, TraceConfig):
            cfg = trace
        elif isinstance(trace, str):
            cfg = cls(out_dir=trace)
        elif trace is True:
            cfg = cls()
        else:                              # None: env-driven
            env = os.environ.get("WF_TRACE", "")
            if env in ("", "0"):
                return None
            cfg = cls() if env == "1" else cls(out_dir=env)
        sample = os.environ.get("WF_TRACE_SAMPLE", "")
        if sample:
            cfg = dataclasses.replace(cfg, sample_every=int(sample))
        return cfg


# ---------------------------------------------------------------- trace ids


def _fnv1a32(s: str) -> int:
    h = 2166136261
    for ch in s.encode():
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h


def mint_trace_id(run_id: str, stream: int, pos: int) -> int:
    """THE deterministic id: a pure function of (run id, source stream,
    offered-batch position) — replay after a supervised restore re-offers the
    same positions and therefore re-mints the same ids.  Layout: a 31-bit
    namespace hash in the high word, the position in the low word (so tooling
    can decode the position back out with ``trace_pos``)."""
    h = _fnv1a32(f"{run_id}/{stream}") & 0x7FFFFFFF
    return (h << 32) | (pos & 0xFFFFFFFF)


def trace_pos(tid: int) -> int:
    """Offered-batch position encoded in a position-mode trace id."""
    return int(tid) & 0xFFFFFFFF


def tid_of(batch) -> Optional[int]:
    """Trace id riding on ``batch``, or None (untraced / tracing off)."""
    return getattr(batch, TRACE_META_ATTR, None)


def carry(src, dst) -> None:
    """Propagate the trace id across an operator hop (compiled pushes return
    NEW Batch objects; the sidecar attribute does not survive jit)."""
    tid = getattr(src, TRACE_META_ATTR, None)
    if tid is not None and dst is not None:
        object.__setattr__(dst, TRACE_META_ATTR, tid)


# ----------------------------------------------------------- flight recorder


# the no-lock hot path IS the design: every segment has exactly one writer
# (its owning thread — driver or a stage body); cross-thread readers
# (records/abort_open) either hold the registry lock and tolerate a ring
# slot landing late, or require the owner joined/dead first
class _Segment:  # wf-lint: single-writer[driver, stage]
    """One thread's pre-allocated slice of the flight recorder.  Single
    writer (the owning thread) — no lock; ``idx`` only grows, slot
    ``idx % capacity`` is overwritten on wrap."""

    __slots__ = ("buf", "idx", "capacity", "thread", "owner", "open_spans",
                 "minted")

    def __init__(self, capacity: int, owner: threading.Thread):
        self.buf: List[Optional[tuple]] = [None] * capacity
        self.idx = 0
        self.capacity = capacity
        self.owner = owner
        self.thread = owner.name
        #: ids minted by this segment's owner — per-thread so concurrent
        #: source loops never race a shared counter; Tracer.minted sums
        self.minted = 0
        #: spans begun but not yet ended on this thread (tid, stage) — lets
        #: a supervisor close them on the restore path so the export never
        #: contains orphan begin records after a recovery
        self.open_spans: List[tuple] = []

    def add(self, rec: tuple) -> None:
        self.buf[self.idx % self.capacity] = rec
        self.idx += 1

    def records(self) -> List[tuple]:
        if self.idx <= self.capacity:
            return [r for r in self.buf[:self.idx]]
        cut = self.idx % self.capacity
        return [r for r in self.buf[cut:] + self.buf[:cut] if r is not None]

    @property
    def dropped(self) -> int:
        return max(0, self.idx - self.capacity)


class _ServiceSpan:
    """Handle returned by :meth:`Tracer.service`; ``done()`` closes it."""

    __slots__ = ("_tracer", "_seg", "tid", "stage")

    def __init__(self, tracer: "Tracer", seg: _Segment, tid: int, stage: str):
        self._tracer = tracer
        self._seg = seg
        self.tid = tid
        self.stage = stage

    def done(self) -> None:
        try:
            self._seg.open_spans.remove((self.tid, self.stage))
        except ValueError:
            return                      # already closed by abort_open — a
            #                             second end would orphan-pair
        self._seg.add((time.perf_counter(), self.tid, self.stage,
                       K_END, None))


class Tracer:
    """Per-run tracing state: id minting + the flight recorder + dump.

    Lifecycle mirrors the event journal: ``start()`` installs the tracer as
    the process-global active tracer (runtime call sites reach it through
    the module-level helpers below, one None check when off), ``finish()``
    dumps ``flight.jsonl`` + ``meta.json`` into ``config.out_dir`` and
    deactivates.  ``finish`` is idempotent and runs in driver ``finally``
    blocks."""

    def __init__(self, config: TraceConfig, name: str = "run"):
        self.config = config
        self.name = name
        self.run_id = config.run_id or name
        self.sample_every = int(config.sample_every)
        self._segments: List[_Segment] = []
        self._seg_lock = threading.Lock()
        self._tls = threading.local()
        self._seq = 0                     # "sequence" id mode counter
        self._seq_lock = threading.Lock()
        self._finished = False
        #: clock sync captured at start: journal records carry
        #: ``time.monotonic()``, flight records ``time.perf_counter()`` —
        #: the exporters map between the two with this pair
        self.perf_t0 = time.perf_counter()
        self.mono_t0 = time.monotonic()
        self.wall_t0 = time.time()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Tracer":
        os.makedirs(self.config.out_dir, exist_ok=True)
        set_active(self)
        _journal.record("trace_start", run_id=self.run_id,
                        sample_every=self.sample_every, ids=self.config.ids)
        return self

    def finish(self) -> Optional[str]:
        """Dump the flight recorder; returns the flight.jsonl path (None on
        repeat calls)."""
        if self._finished:
            return None
        self._finished = True
        if get_active() is self:
            set_active(None)
        _journal.record("trace_end", run_id=self.run_id, minted=self.minted)
        recs = self.records()
        path = os.path.join(self.config.out_dir, "flight.jsonl")
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        with open(os.path.join(self.config.out_dir, "meta.json"), "w") as f:
            json.dump(self.meta(), f, indent=1)
        return path

    @property
    def minted(self) -> int:
        """Total ids minted, summed over the per-thread segments (each owner
        thread counts its own — no shared-counter race)."""
        with self._seg_lock:
            return sum(s.minted for s in self._segments)

    def meta(self) -> dict:
        with self._seg_lock:            # a stage thread may be registering
            segs = list(self._segments)  # its segment concurrently
        return {"run_id": self.run_id, "name": self.name,
                "ids": self.config.ids, "sample_every": self.sample_every,
                "ring_capacity": self.config.ring_capacity,
                "minted": self.minted,
                "dropped": sum(s.dropped for s in segs),
                "perf_t0": self.perf_t0, "mono_t0": self.mono_t0,
                "wall_t0": self.wall_t0}

    # -- recording ---------------------------------------------------------

    def _seg(self) -> _Segment:
        seg = getattr(self._tls, "seg", None)
        if seg is None:
            seg = _Segment(self.config.ring_capacity,
                           threading.current_thread())
            self._tls.seg = seg
            with self._seg_lock:
                self._segments.append(seg)
        return seg

    def ingest(self, batch, pos: int, stream: int = 0,
               extras: Optional[dict] = None) -> Optional[int]:
        """Source boundary: sample + mint + attach + record.  Returns the
        minted id (None when the batch fell outside the sample).

        ``extras`` rides the ingest record verbatim (flattened into the
        flight.jsonl row by :meth:`records`) — the serving runtime joins
        the wire coordinates here: ``tenant``/``seq`` plus ``wire_ms``
        (client send -> socket receipt) and ``queue_ms`` (receipt -> drive
        pickup), so the per-tenant trace report can attribute time spent
        BEFORE the batch existed on this host."""
        if pos % self.sample_every:
            return None
        if self.config.ids == "sequence":
            with self._seq_lock:
                n = self._seq
                self._seq += 1
            tid = mint_trace_id(self.run_id, stream, n)
        else:
            tid = mint_trace_id(self.run_id, stream, pos)
        object.__setattr__(batch, TRACE_META_ATTR, tid)
        seg = self._seg()
        seg.minted += 1
        extra = {"pos": int(pos), "stream": int(stream)}
        if extras:
            extra.update(extras)
        seg.add((time.perf_counter(), tid, "ingest", K_INGEST, extra))
        return tid

    def event(self, batch, stage: str, kind: str) -> None:
        """Ring-edge record (``stage`` is the edge label) for a traced batch;
        no-op for untraced ones."""
        tid = getattr(batch, TRACE_META_ATTR, None)
        if tid is None:
            return
        self._seg().add((time.perf_counter(), tid, stage, kind, None))

    def service(self, batch, stage: str,
                k: Optional[int] = None) -> Optional[_ServiceSpan]:
        """Open a service span for a traced batch; the caller invokes
        ``.done()`` after the stage's work.  None for untraced batches.

        ``k`` marks FUSED-GROUP membership (scan dispatch, ``WF_DISPATCH``
        with K>1): all K member spans cover the same one compiled launch, so
        the begin record carries ``k`` and the report's per-batch service
        attribution divides the span by it — without the marker a fused
        group would charge its whole service span to every member and the
        stage breakdown would overcount K-fold."""
        tid = getattr(batch, TRACE_META_ATTR, None)
        if tid is None:
            return None
        seg = self._seg()
        extra = {"k": int(k)} if k is not None and k > 1 else None
        seg.add((time.perf_counter(), tid, stage, K_BEGIN, extra))
        seg.open_spans.append((tid, stage))
        return _ServiceSpan(self, seg, tid, stage)

    def stall(self, stage: str) -> _ServiceSpan:
        """Batch-less span (governor throttle episodes): records on the
        given pseudo-stage with trace id 0."""
        seg = self._seg()
        seg.add((time.perf_counter(), 0, stage, K_BEGIN, None))
        seg.open_spans.append((0, stage))
        return _ServiceSpan(self, seg, 0, stage)

    def abort_open(self, reason: str) -> int:
        """Close every span left open by a failed attempt: spans on THIS
        thread (the supervised drivers' step usually runs on the driver
        thread) and spans on segments whose owning thread has exited (a
        ``step_timeout`` watchdog worker that died with the fault — the
        supervisors join abandoned workers before calling this, so a
        finished-or-dead worker's segment has no concurrent writer; a
        genuinely HUNG worker stays alive and keeps its spans, which the
        exporter then drops and counts as unmatched).  Each closed span gets
        an end record tagged with the abort reason — B/E stay matched, the
        aborted attempt stays visible in the trace.  Returns the number of
        spans closed."""
        cur = threading.current_thread()
        with self._seg_lock:
            segs = list(self._segments)
        now = time.perf_counter()
        n = 0
        for seg in segs:
            if not seg.open_spans:
                continue
            if seg.owner is not cur and seg.owner.is_alive():
                continue                    # live foreign writer: hands off
            for tid, stage in seg.open_spans:
                seg.add((now, tid, stage, K_END, {"aborted": reason}))
                n += 1
            seg.open_spans.clear()
        return n

    def snapshot_chrome(self, journal_events: Optional[list] = None) -> dict:
        """Chrome trace-event dump of the CURRENT ring contents, without
        finishing the tracer — the mid-run flight-recorder dump hook the
        SLO engine's incident capture rides (``observability/slo.py``).
        Safe from any thread: :meth:`records` reads each per-thread segment
        through its ring-window snapshot, and open spans simply have no end
        record yet (the exporter drops and counts unmatched begins)."""
        return to_chrome_trace(self.records(), journal_events=journal_events,
                               meta=self.meta())

    def records(self) -> List[dict]:
        """Every surviving record as dicts, globally sorted by timestamp."""
        with self._seg_lock:
            segs = list(self._segments)
        out = []
        for seg in segs:
            for (t, tid, stage, kind, extra) in seg.records():
                rec = {"t": t, "tid": tid, "stage": stage, "kind": kind,
                       "thread": seg.thread}
                if extra:
                    rec.update(extra)
                out.append(rec)
        out.sort(key=lambda r: r["t"])
        return out


# ------------------------------------------------- process-global active hook

#: the active tracer (set by a driver's run for its duration).  Runtime call
#: sites go through the module-level helpers so a disabled tracer costs one
#: attribute load + None check — the ``journal.record`` pattern.
_active: Optional[Tracer] = None


def set_active(tracer: Optional[Tracer]) -> None:
    global _active
    _active = tracer


def get_active() -> Optional[Tracer]:
    return _active


def ingest(batch, pos: int, stream: int = 0,
           extras: Optional[dict] = None) -> None:
    tr = _active
    if tr is not None:
        tr.ingest(batch, pos, stream, extras=extras)


def event(batch, stage: str, kind: str) -> None:
    tr = _active
    if tr is not None:
        tr.event(batch, stage, kind)


def service(batch, stage: str, k: Optional[int] = None
            ) -> Optional[_ServiceSpan]:
    tr = _active
    if tr is not None:
        return tr.service(batch, stage, k=k)
    return None


def stall(stage: str) -> Optional[_ServiceSpan]:
    tr = _active
    if tr is not None:
        return tr.stall(stage)
    return None


def abort_open(reason: str) -> None:
    tr = _active
    if tr is not None:
        tr.abort_open(reason)


# ------------------------------------------------------------------ loading


def load_flight(trace_dir: str):
    """(records, meta) from a Tracer dump directory."""
    with open(os.path.join(trace_dir, "meta.json")) as f:
        meta = json.load(f)
    records = []
    with open(os.path.join(trace_dir, "flight.jsonl")) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records, meta


def _mono_to_perf(meta: Optional[dict]):
    """Journal timestamps (``time.monotonic``) -> flight-recorder timeline
    (``time.perf_counter``) via the clock pair captured at Tracer.start."""
    if not meta or "mono_t0" not in meta or "perf_t0" not in meta:
        return None
    off = meta["perf_t0"] - meta["mono_t0"]
    return lambda t: t + off


# --------------------------------------------------- Chrome trace-event JSON


def to_chrome_trace(records: List[dict], journal_events: Optional[list] = None,
                    meta: Optional[dict] = None) -> dict:
    """Render flight-recorder records (+ optionally the event journal) as a
    Chrome trace-event JSON object (Perfetto / chrome://tracing loadable).

    Layout: pid 1 = the flight recorder, one tid (track) per stage — operator
    chains, the sink, and one track per SPSC ring edge whose slices are queue
    residency (enqueue -> dequeue), with flow arrows connecting producer to
    consumer; pid 2 = the runtime journal (checkpoint/restore/throttle spans,
    shed/dead-letter instants).  ``ts`` is microseconds from the earliest
    record; B/E events are emitted matched (unpaired begins are dropped and
    counted in the returned ``meta`` section)."""
    records = sorted(records, key=lambda r: r["t"])
    t0 = records[0]["t"] if records else 0.0
    mapper = _mono_to_perf(meta)
    jevents = sorted(journal_events or [], key=lambda e: e.get("t", 0.0))
    if jevents and mapper is not None:
        jt = [mapper(e["t"]) for e in jevents if "t" in e]
        if jt:
            t0 = min([t0] + jt) if records else min(jt)

    def us(t):
        return round((t - t0) * 1e6, 3)

    events: List[dict] = []
    tracks: Dict[str, int] = {}

    def track(stage: str) -> int:
        k = tracks.get(stage)
        if k is None:
            k = tracks[stage] = len(tracks) + 1
            events.append({"ph": "M", "pid": 1, "tid": k, "ts": 0,
                           "name": "thread_name",
                           "args": {"name": stage}})
        return k

    events.append({"ph": "M", "pid": 1, "tid": 0, "ts": 0,
                   "name": "process_name",
                   "args": {"name": "windflow flight recorder"}})

    open_begin: Dict[tuple, dict] = {}     # (tid, stage) -> begin record
    enq_at: Dict[tuple, dict] = {}         # (tid, edge) -> enqueue record
    dropped_begins = 0
    flow_seq = 0
    for r in records:
        tid, stage, kind = r["tid"], r["stage"], r["kind"]
        if kind == K_INGEST:
            events.append({"ph": "i", "pid": 1, "tid": track("ingest"),
                           "ts": us(r["t"]), "name": "ingest", "s": "t",
                           "args": {"trace_id": hex(tid),
                                    "pos": r.get("pos")}})
        elif kind == K_BEGIN:
            prev = open_begin.get((tid, stage))
            if prev is not None:
                dropped_begins += 1       # crashed attempt with no abort rec
            open_begin[(tid, stage)] = r
        elif kind == K_END:
            b = open_begin.pop((tid, stage), None)
            if b is None:
                continue                  # end without begin (ring wrapped)
            args: Dict[str, Any] = {"trace_id": hex(tid)}
            if b.get("k"):
                args["fused_k"] = b["k"]  # scan-dispatch group membership
            if r.get("aborted"):
                args["aborted"] = r["aborted"]
            tk = track(stage)
            events.append({"ph": "B", "pid": 1, "tid": tk, "ts": us(b["t"]),
                           "name": stage, "args": args})
            events.append({"ph": "E", "pid": 1, "tid": tk, "ts": us(r["t"]),
                           "name": stage})
        elif kind == K_ENQ:
            enq_at[(tid, stage)] = r
        elif kind == K_DEQ:
            e = enq_at.pop((tid, stage), None)
            if e is None:
                continue
            tk = track(f"ring {stage}")
            events.append({"ph": "X", "pid": 1, "tid": tk, "ts": us(e["t"]),
                           "dur": max(us(r["t"]) - us(e["t"]), 0.001),
                           "name": "queued",
                           "args": {"trace_id": hex(tid), "edge": stage}})
            flow_seq += 1
            fid = f"{tid:x}.{flow_seq}"
            events.append({"ph": "s", "pid": 1, "tid": tk, "ts": us(e["t"]),
                           "name": "ring", "cat": "ring", "id": fid})
            events.append({"ph": "f", "pid": 1, "tid": tk, "ts": us(r["t"]),
                           "name": "ring", "cat": "ring", "id": fid,
                           "bp": "e"})
    dropped_begins += len(open_begin)

    # runtime journal: spans as matched B/E per (event name, span seq),
    # point events as instants — on pid 2 so they sit under the flight tracks
    jtracks: Dict[str, int] = {}
    jopen: Dict[tuple, dict] = {}
    if jevents and mapper is not None:
        events.append({"ph": "M", "pid": 2, "tid": 0, "ts": 0,
                       "name": "process_name",
                       "args": {"name": "windflow runtime journal"}})

        def jtrack(name: str) -> int:
            k = jtracks.get(name)
            if k is None:
                k = jtracks[name] = len(jtracks) + 1
                events.append({"ph": "M", "pid": 2, "tid": k, "ts": 0,
                               "name": "thread_name", "args": {"name": name}})
            return k

        for e in jevents:
            if "t" not in e or "event" not in e:
                continue
            ts = us(mapper(e["t"]))
            name = e["event"]
            args = {k: v for k, v in e.items()
                    if k not in ("t", "wall", "event", "phase", "span")}
            if e.get("phase") == "begin":
                jopen[(name, e.get("span"))] = e
            elif e.get("phase") == "end":
                b = jopen.pop((name, e.get("span")), None)
                if b is None:
                    continue
                tk = jtrack(name)
                events.append({"ph": "B", "pid": 2, "tid": tk,
                               "ts": us(mapper(b["t"])), "name": name,
                               "args": args})
                events.append({"ph": "E", "pid": 2, "tid": tk, "ts": ts,
                               "name": name})
            else:
                events.append({"ph": "i", "pid": 2, "tid": jtrack(name),
                               "ts": ts, "name": name, "s": "t",
                               "args": args})

    events.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "M" else 1))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"run_id": (meta or {}).get("run_id"),
                          "dropped_begins": dropped_begins,
                          "flight_records": len(records)}}


# ------------------------------------------------------- critical-path report


def _batch_lifecycles(records: List[dict]) -> Dict[int, dict]:
    """Fold records into per-trace-id lifecycles: ingest time, end time,
    per-stage service durations, per-edge queue waits, aborted-span count.

    Fused-dispatch apportionment: a span whose begin record carries ``k``
    (scan dispatch, K>1) covers ONE compiled launch shared by K group
    members, so each member is charged ``span / k`` — the per-batch drill-
    down stays honest under ``WF_DISPATCH`` instead of charging the whole
    group service span to every member."""
    out: Dict[int, dict] = {}

    def life(tid):
        lc = out.get(tid)
        if lc is None:
            lc = out[tid] = {"tid": tid, "pos": None, "stream": None,
                             "t_ingest": None, "t_end": None,
                             "service": {}, "queue": {}, "aborts": 0,
                             "attempts": {}, "fused": 0,
                             # wire-to-sink coordinates (serving ingest
                             # extras; None for non-serving drivers)
                             "tenant": None, "seq": None,
                             "wire_ms": None, "queue_ms": None}
        return lc

    open_begin: Dict[tuple, tuple] = {}    # (tid, stage) -> (t, k or None)
    enq_at: Dict[tuple, float] = {}
    for r in sorted(records, key=lambda x: x["t"]):
        tid, stage, kind, t = r["tid"], r["stage"], r["kind"], r["t"]
        if tid == 0:
            continue                      # batch-less stall spans
        lc = life(tid)
        lc["t_end"] = t if lc["t_end"] is None else max(lc["t_end"], t)
        if kind == K_INGEST:
            if lc["t_ingest"] is None:    # replay re-ingests: keep the first
                lc["t_ingest"] = t
                lc["pos"] = r.get("pos")
                lc["stream"] = r.get("stream")
                lc["tenant"] = r.get("tenant")
                lc["seq"] = r.get("seq")
                lc["wire_ms"] = r.get("wire_ms")
                lc["queue_ms"] = r.get("queue_ms")
        elif kind == K_BEGIN:
            open_begin[(tid, stage)] = (t, r.get("k"))
            lc["attempts"][stage] = lc["attempts"].get(stage, 0) + 1
        elif kind == K_END:
            b = open_begin.pop((tid, stage), None)
            if b is not None:
                t0, k = b
                dur = t - t0
                if k and int(k) > 1:
                    dur /= int(k)         # fused group: this batch's share
                    lc["fused"] += 1
                lc["service"][stage] = lc["service"].get(stage, 0.0) + dur
            if r.get("aborted"):
                lc["aborts"] += 1
        elif kind == K_ENQ:
            enq_at[(tid, stage)] = t
        elif kind == K_DEQ:
            e = enq_at.pop((tid, stage), None)
            if e is not None:
                lc["queue"][stage] = lc["queue"].get(stage, 0.0) + (t - e)
    return out


def _journal_intervals(jevents: list, name: str, mapper) -> List[tuple]:
    """(t_begin, t_end, fields) for every completed journal span ``name``,
    mapped onto the flight-recorder timeline."""
    if mapper is None:
        return []
    out, jopen = [], {}
    for e in sorted(jevents, key=lambda x: x.get("t", 0.0)):
        if e.get("event") != name or "t" not in e:
            continue
        if e.get("phase") == "begin":
            jopen[e.get("span")] = e
        elif e.get("phase") == "end":
            b = jopen.pop(e.get("span"), None)
            if b is not None:
                out.append((mapper(b["t"]), mapper(e["t"]), e))
    return out


def _throttle_intervals(jevents: list, mapper) -> List[tuple]:
    """throttle/throttle_end are point-event pairs (not spans): pair them
    sequentially per edge."""
    if mapper is None:
        return []
    out, started = [], {}
    for e in sorted(jevents, key=lambda x: x.get("t", 0.0)):
        ev = e.get("event")
        if ev == "throttle" and "t" in e:
            started[e.get("edge")] = e
        elif ev == "throttle_end" and "t" in e:
            b = started.pop(e.get("edge"), None)
            if b is not None:
                out.append((mapper(b["t"]), mapper(e["t"]), e))
    return out


def _overlap(a0: float, a1: float, iv: List[tuple]) -> float:
    tot = 0.0
    for (b0, b1, _f) in iv:
        tot += max(0.0, min(a1, b1) - max(a0, b0))
    return tot


def critical_path_report(records: List[dict],
                         journal_events: Optional[list] = None,
                         snapshot: Optional[dict] = None,
                         meta: Optional[dict] = None, top: int = 5) -> str:
    """Human-readable critical-path breakdown: per-stage service vs queue
    wait vs governor throttle vs shed/restart attribution (correlated from
    the event journal), plus a drill-down of the slowest traced batches and
    the latency exemplars from the metrics snapshot."""
    jevents = journal_events or []
    mapper = _mono_to_perf(meta)
    lives = _batch_lifecycles(records)
    restores = _journal_intervals(jevents, "restore", mapper)
    throttles = _throttle_intervals(jevents, mapper)
    # shed events journal (stream, per-root offered pos) — the coordinates
    # trace ids are minted from; events from single-stream drivers omit the
    # stream and match on position alone
    shed_keys = {(e.get("stream"), e.get("pos")) for e in jevents
                 if e.get("event") == "shed"}
    shed_pos = {p for _s, p in shed_keys}
    dead_pos = {e.get("at_batch") for e in jevents
                if e.get("event") == "dead_letter"}
    # event-time drop forensics (event_time monitoring): each record carries
    # the trace coordinates of the sampled batch whose readback surfaced it
    late_drops = [e for e in jevents if e.get("event") == "lateness_drop"]

    def _is_shed(lc) -> bool:
        return ((lc["stream"], lc["pos"]) in shed_keys
                or (None, lc["pos"]) in shed_keys)

    lines: List[str] = []
    rid = (meta or {}).get("run_id", "?")
    lines.append(f"== windflow trace report: run {rid!r} "
                 f"({len(lives)} traced batches, {len(records)} records) ==")

    # -- aggregate per-stage critical path --------------------------------
    svc_tot: Dict[str, float] = {}
    q_tot: Dict[str, float] = {}
    for lc in lives.values():
        for s, d in lc["service"].items():
            svc_tot[s] = svc_tot.get(s, 0.0) + d
        for s, d in lc["queue"].items():
            q_tot[s] = q_tot.get(s, 0.0) + d
    lines.append("")
    lines.append("stage breakdown (summed over traced batches):")
    for s, d in sorted(svc_tot.items(), key=lambda kv: -kv[1]):
        lines.append(f"  service      {s:<24} {d * 1e3:10.3f} ms")
    for s, d in sorted(q_tot.items(), key=lambda kv: -kv[1]):
        lines.append(f"  queue-wait   {s:<24} {d * 1e3:10.3f} ms")
    thr_s = sum(b1 - b0 for b0, b1, _ in throttles)
    if throttles:
        lines.append(f"  governor-throttle {len(throttles)} episodes "
                     f"{thr_s * 1e3:10.3f} ms")
    res_s = sum(b1 - b0 for b0, b1, _ in restores)
    if restores:
        lines.append(f"  restart/restore   {len(restores)} restores "
                     f"{res_s * 1e3:10.3f} ms")
    if shed_pos:
        lines.append(f"  shed              {len(shed_pos)} batches "
                     f"(admission) at pos "
                     f"{sorted(p for p in shed_pos if p is not None)}")
    if dead_pos:
        lines.append(f"  dead-letter       {len(dead_pos)} batches at pos "
                     f"{sorted(p for p in dead_pos if p is not None)}")
    if late_drops:
        lines.append("")
        lines.append("event-time drops (lateness_drop journal; joined to "
                     "traced batches by the sampled readback's coordinates):")
        for e in late_drops:
            where = ""
            if e.get("pos") is not None:
                tid = e.get("tid")
                traced = tid is not None and int(tid) in lives
                where = (f"  at/before pos={e['pos']}"
                         f" (batch {int(tid):#x}"
                         f"{', traced' if traced else ''})"
                         if tid is not None else f"  at/before pos={e['pos']}")
            lines.append(f"  {e.get('op', '?'):<24} {e.get('kind', '?'):<16} "
                         f"+{e.get('n', 0)} (total {e.get('total', '?')})"
                         f"{where}")

    # -- per-tenant wire-to-sink attribution (serving) --------------------
    by_tenant: Dict[str, list] = {}
    for lc in lives.values():
        if lc.get("tenant") is not None:
            by_tenant.setdefault(str(lc["tenant"]), []).append(lc)
    if by_tenant:
        lines.append("")
        lines.append("per-tenant wire-to-sink attribution (serving ingest; "
                     "wire = client send -> socket receipt, queue = receipt "
                     "-> drive pickup + ring waits, service = stage spans):")

        def _segments(lc) -> dict:
            wire = (lc.get("wire_ms") or 0.0) / 1e3
            qsrc = (lc.get("queue_ms") or 0.0) / 1e3
            svc = sum(lc["service"].values())
            qring = sum(lc["queue"].values())
            t0, t1 = lc["t_ingest"], lc["t_end"]
            host = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
            return {"wire": wire, "queue": qsrc + qring, "service": svc,
                    "e2e": wire + qsrc + host}

        for tenant, lcs in sorted(by_tenant.items()):
            segs = [_segments(lc) for lc in lcs]
            shed_n = sum(1 for lc in lcs if _is_shed(lc))
            head = f"  tenant {tenant!r}: {len(lcs)} traced requests"
            if shed_n:
                head += f"  ({shed_n} shed at admission)"
            lines.append(head)
            worst_seg, worst_max = "", -1.0
            for name in ("wire", "queue", "service", "e2e"):
                vals = [s[name] for s in segs]
                avg, mx = sum(vals) / len(vals), max(vals)
                lines.append(f"    {name:<8} avg={avg * 1e3:10.3f} ms  "
                             f"max={mx * 1e3:10.3f} ms")
                if name != "e2e" and mx > worst_max:
                    worst_seg, worst_max = name, mx
            slowest = max(zip(segs, lcs), key=lambda p: p[0]["e2e"])
            lines.append(f"    slowest segment: {worst_seg}  "
                         f"(worst request: batch {slowest[1]['tid']:#x} "
                         f"seq={slowest[1].get('seq')} "
                         f"e2e={slowest[0]['e2e'] * 1e3:.3f} ms)")

    # -- dispatch-bound classifier (health monitoring) --------------------
    health = (snapshot or {}).get("health") or {}
    dt = health.get("device_time") or {}
    if dt:
        lines.append("")
        lines.append("device-time attribution (health ledger; sampled "
                     "host-dispatch vs device ms per stage):")
        bound = health.get("dispatch_bound") or {}
        for stage, row in sorted(dt.items(),
                                 key=lambda kv: -(kv[1].get("dispatch_ratio")
                                                  or 0.0)):
            ratio = row.get("dispatch_ratio")
            flag = "  [DISPATCH-BOUND -> fusion candidate]" \
                if stage in bound else ""
            lines.append(
                f"  {stage:<24} device={row.get('device_ms', 0):10.3f} ms  "
                f"dispatch={row.get('dispatch_ms', 0):10.3f} ms  "
                f"ratio={ratio if ratio is not None else '—'}{flag}")
        comp = health.get("compile") or {}
        if comp:
            lines.append(
                f"  compile ledger: {comp.get('compiles', 0)} compiles "
                f"({comp.get('retraces', 0)} shape retraces, "
                f"{comp.get('retraces_unexpected', 0)} UNEXPECTED), "
                f"{comp.get('compile_s_total', 0)} s total")

    # -- per-batch phase attribution --------------------------------------
    def phases(lc) -> dict:
        t0, t1 = lc["t_ingest"], lc["t_end"]
        if t0 is None or t1 is None:
            return {"total": 0.0, "service": 0.0, "queue": 0.0,
                    "throttle": 0.0, "restart": 0.0, "other": 0.0}
        total = t1 - t0
        svc = sum(lc["service"].values())
        q = sum(lc["queue"].values())
        thr = _overlap(t0, t1, throttles)
        res = _overlap(t0, t1, restores)
        return {"total": total, "service": svc, "queue": q, "throttle": thr,
                "restart": res,
                "other": max(total - svc - q - thr - res, 0.0)}

    def flags(lc) -> str:
        f = []
        if lc.get("fused"):
            # service figures are the batch's 1/k share of fused launches
            f.append("FUSED")
        if _is_shed(lc):
            f.append("SHED")
        if lc["pos"] in dead_pos:
            f.append("DEAD-LETTER")
        if lc["aborts"] or _overlap(lc["t_ingest"] or 0.0,
                                    lc["t_end"] or 0.0, restores) > 0.0:
            f.append("RESTART-AFFECTED")
        return ",".join(f)

    def render(lc, prefix="  ") -> List[str]:
        ph = phases(lc)
        head = (f"{prefix}batch {lc['tid']:#x} pos={lc['pos']} "
                f"total={ph['total'] * 1e3:.3f} ms"
                + (f"  [{flags(lc)}]" if flags(lc) else ""))
        parts = (f"{prefix}  service={ph['service'] * 1e3:.3f} ms  "
                 f"queue-wait={ph['queue'] * 1e3:.3f} ms  "
                 f"throttle={ph['throttle'] * 1e3:.3f} ms  "
                 f"restart={ph['restart'] * 1e3:.3f} ms  "
                 f"other={ph['other'] * 1e3:.3f} ms")
        out = [head, parts]
        for s, n in sorted(lc["attempts"].items()):
            if n > 1:
                out.append(f"{prefix}  {s}: {n} attempts "
                           f"({lc['aborts']} aborted spans)")
        return out

    slow = sorted(lives.values(), key=lambda lc: -phases(lc)["total"])[:top]
    lines.append("")
    lines.append(f"slowest {len(slow)} traced batches:")
    for lc in slow:
        lines.extend(render(lc))

    # -- exemplars vs snapshot --------------------------------------------
    if snapshot:
        e2e = snapshot.get("e2e_latency_us") or {}
        ex = e2e.get("p99_exemplar")
        lines.append("")
        if ex is not None:
            lines.append(f"p99 exemplar (snapshot e2e histogram, "
                         f"p99={e2e.get('p99')} us): batch {int(ex):#x}")
            lc = lives.get(int(ex))
            if lc is not None:
                lines.extend(render(lc, prefix="    "))
            else:
                lines.append("    (exemplar batch outside the flight "
                             "recorder's retained window)")
        else:
            lines.append("no e2e p99 exemplar in snapshot (tracing and "
                         "monitoring must run together for exemplars)")
    return "\n".join(lines)
