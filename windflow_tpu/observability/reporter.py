"""Periodic reporter thread — the reference's MONITORING-mode per-second dump.

Upstream WindFlow's ``MONITORING`` build runs a reporter that aggregates every
replica's ``Stats_Record`` into a JSON dump once per second (SURVEY §5). Here a
single daemon thread snapshots the :class:`~.metrics.MetricsRegistry` every
``interval_s`` and writes:

- ``snapshot.json``   — the latest graph-level snapshot (atomic replace);
- ``snapshots.jsonl`` — one line per tick (time series for later analysis);
- ``metrics.prom``    — Prometheus text exposition (point a file-based scraper
  or ``node_exporter`` textfile collector at it).

Off by default; started/stopped by the Monitor. ``stop()`` joins the thread and
emits one final snapshot, so no thread outlives the run (tested)."""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from .metrics import MetricsRegistry


def _atomic_write(path: str, data: str) -> None:
    """Write-then-rename (the checkpoint.py convention): a reader — the
    stdlib CLIs wf_state/wf_trace/wf_health poll these files while the run
    is live — can NEVER observe a torn snapshot.json / metrics.prom: either
    the old complete file or the new complete file.  The tmp name carries
    pid + thread id so a reporter tick racing a final ``stop()`` emit (two
    writers, one path) cannot truncate each other's in-flight tmp; flush +
    fsync before the rename so the replace publishes complete bytes, not an
    empty inode, even across a crash."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):            # failed mid-write: no debris
            try:
                os.unlink(tmp)
            except OSError:
                pass


class Reporter:
    def __init__(self, registry: MetricsRegistry, out_dir: str,
                 interval_s: float = 1.0, prometheus: bool = True):
        self.registry = registry
        self.out_dir = out_dir
        self.interval_s = max(0.05, float(interval_s))
        self.prometheus = prometheus
        # bumped by emit(): reporter ticks while running; the driver's final
        # stop() emit runs only after join() — never two writers at once
        self.ticks = 0                      # wf-lint: single-writer[reporter]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(out_dir, exist_ok=True)

    # -- lifecycle --------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # wf-lint: thread-role[reporter]
            target=self._run, name="wf-reporter", daemon=True)
        self._thread.start()

    def stop(self, final: bool = True) -> None:
        """Signal, join, and (by default) write one last snapshot so short
        runs that never crossed an interval still leave artifacts."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        if final:
            self.emit()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- emission ---------------------------------------------------------------------

    def emit(self) -> dict:
        snap = self.registry.snapshot()
        _atomic_write(os.path.join(self.out_dir, "snapshot.json"),
                      json.dumps(snap, indent=1, sort_keys=True))
        with open(os.path.join(self.out_dir, "snapshots.jsonl"), "a") as f:
            f.write(json.dumps(snap) + "\n")
        if self.prometheus:
            _atomic_write(os.path.join(self.out_dir, "metrics.prom"),
                          self.registry.to_prometheus(snap))
        self.ticks += 1
        return snap

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.emit()
            except Exception:       # noqa: BLE001 — a bad tick must not kill
                pass                # the reporter (snapshot retries next tick)
