"""Periodic reporter thread — the reference's MONITORING-mode per-second dump.

Upstream WindFlow's ``MONITORING`` build runs a reporter that aggregates every
replica's ``Stats_Record`` into a JSON dump once per second (SURVEY §5). Here a
single daemon thread snapshots the :class:`~.metrics.MetricsRegistry` every
``interval_s`` and writes:

- ``snapshot.json``   — the latest graph-level snapshot (atomic replace);
- ``snapshots.jsonl`` — one line per tick (time series for later analysis);
- ``metrics.prom``    — Prometheus text exposition (point a file-based scraper
  or ``node_exporter`` textfile collector at it).

Off by default; started/stopped by the Monitor. ``stop()`` joins the thread and
emits one final snapshot, so no thread outlives the run (tested)."""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Optional

from .metrics import MetricsRegistry


def _atomic_write(path: str, data: str) -> None:
    """Write-then-rename (the checkpoint.py convention): a reader — the
    stdlib CLIs wf_state/wf_trace/wf_health poll these files while the run
    is live — can NEVER observe a torn snapshot.json / metrics.prom: either
    the old complete file or the new complete file.  The tmp name carries
    pid + thread id so a reporter tick racing a final ``stop()`` emit (two
    writers, one path) cannot truncate each other's in-flight tmp; flush +
    fsync before the rename so the replace publishes complete bytes, not an
    empty inode, even across a crash."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):            # failed mid-write: no debris
            try:
                os.unlink(tmp)
            except OSError:
                pass


class Reporter:
    def __init__(self, registry: MetricsRegistry, out_dir: str,
                 interval_s: float = 1.0, prometheus: bool = True,
                 slo_engine=None, snapshot_keep: Optional[int] = None,
                 telemetry_agent=None):
        self.registry = registry
        self.out_dir = out_dir
        self.interval_s = max(0.05, float(interval_s))
        self.prometheus = prometheus
        #: fleet telemetry agent (observability/fleet.py TelemetryAgent,
        #: None = plane off): its stats are stamped into every snapshot
        #: BEFORE the files land (so the artifacts carry the
        #: windflow_telemetry_* gauges) and the written snapshot is OFFERED
        #: after — a bounded deque append, never a socket wait, so the tick
        #: cadence is independent of the aggregator's health by construction
        self.telemetry = telemetry_agent
        #: SLO engine (observability/slo.py) evaluated INSIDE every tick,
        #: right after the registry snapshot and before the files land —
        #: the written snapshot.json/snapshots.jsonl carry its "slo"
        #: section, and PAGE transitions capture incident bundles on this
        #: thread (the engine is single-writer for the same reason ticks
        #: is: the final stop() emit runs only after join())
        self.slo = slo_engine
        #: keep-last-N-lines retention for snapshots.jsonl (None/0 =
        #: unlimited, today's behavior): a long-running service's time
        #: series must not grow without bound.  Rotation is an amortized
        #: atomic rewrite on THIS thread (trim to N once the file reaches
        #: 2N lines, so steady state appends instead of rewriting every
        #: tick) — a reader polling the file sees either the pre- or
        #: post-trim file, never a truncated line
        self.snapshot_keep = int(snapshot_keep) if snapshot_keep else None
        # bumped by emit(): reporter ticks while running; the driver's final
        # stop() emit runs only after join() — never two writers at once
        self.ticks = 0                      # wf-lint: single-writer[reporter]
        # SLO-engine observe() failures (same single-writer discipline): a
        # broken signal extractor must not kill the tick, but the engine
        # whose whole job is alerting dying silently would be worse — the
        # count lands in every snapshot and the first failure warns once
        self.slo_errors = 0                 # wf-lint: single-writer[reporter]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(out_dir, exist_ok=True)
        self._jsonl_path = os.path.join(out_dir, "snapshots.jsonl")
        # resume-aware line count (same single-writer discipline as ticks)
        self._jsonl_lines = 0               # wf-lint: single-writer[reporter]
        if self.snapshot_keep and os.path.exists(self._jsonl_path):
            with open(self._jsonl_path) as f:
                self._jsonl_lines = sum(1 for _ in f)

    # -- lifecycle --------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # wf-lint: thread-role[reporter]
            target=self._run, name="wf-reporter", daemon=True)
        self._thread.start()

    def stop(self, final: bool = True) -> None:
        """Signal, join, and (by default) write one last snapshot so short
        runs that never crossed an interval still leave artifacts."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        if final:
            self.emit()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- emission ---------------------------------------------------------------------

    def emit(self) -> dict:
        snap = self.registry.snapshot()
        if self.telemetry is not None:
            snap["telemetry"] = self.telemetry.stats()
        if self.slo is not None:
            try:
                self.slo.observe(snap)
            except Exception as e:  # noqa: BLE001 — a bad SLO tick must not
                # kill the reporter (the snapshot still lands), but it must
                # not die SILENTLY either: the snapshot records the error +
                # count, and the first failure warns on stderr — otherwise a
                # broken extractor reads as "all SLOs OK" for the whole run
                self.slo_errors += 1
                snap["slo_error"] = {"error": f"{type(e).__name__}: {e}",
                                     "count": self.slo_errors}
                if self.slo_errors == 1:
                    print(f"wf reporter: SLO engine failed on tick "
                          f"{self.ticks + 1} ({type(e).__name__}: {e}) — "
                          f"burn-rate alerting is degraded; see "
                          f"snapshot['slo_error']", file=sys.stderr)
        _atomic_write(os.path.join(self.out_dir, "snapshot.json"),
                      json.dumps(snap, indent=1, sort_keys=True))
        with open(self._jsonl_path, "a") as f:
            f.write(json.dumps(snap) + "\n")
        self._jsonl_lines += 1
        if self.snapshot_keep and self._jsonl_lines >= 2 * self.snapshot_keep:
            # amortized: trim back to keep-N only once the file doubles —
            # trimming on every tick past N would re-read and rewrite the
            # whole series each second for the lifetime of a long-running
            # service (the exact deployment retention targets).  Readers
            # tolerate either side of the rewrite; the file is bounded at
            # 2N-1 lines and always ends with the newest ticks
            with open(self._jsonl_path) as f:
                lines = f.readlines()
            kept = lines[-self.snapshot_keep:]
            _atomic_write(self._jsonl_path, "".join(kept))
            self._jsonl_lines = len(kept)
        if self.prometheus:
            _atomic_write(os.path.join(self.out_dir, "metrics.prom"),
                          self.registry.to_prometheus(snap))
        if self.telemetry is not None:
            try:
                self.telemetry.offer(snap)
            except Exception:  # noqa: BLE001 — the telemetry plane is
                pass           # best-effort; it must never cost a tick
        self.ticks += 1
        return snap

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.emit()
            except Exception:       # noqa: BLE001 — a bad tick must not kill
                pass                # the reporter (snapshot retries next tick)
