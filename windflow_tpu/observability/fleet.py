"""Fleet telemetry plane — live streaming aggregation + fleet-level SLOs.

The recording stack (histograms, journal, watermark map, health ledger) and
the judging stack (PR 15's burn-rate SLO engine) are per host: fleet state
exists only as ``merge_snapshots`` run offline by a human pointing
``wf_health.py --merge`` at N directories after the fact.  This module makes
the same fold LIVE — the fleet-scale analogue of the source paper's
per-replica ``Stats_Record`` monitoring tree lifted off a single
shared-memory node.  Two halves:

- :class:`TelemetryAgent` rides each host's Reporter tick: the freshly
  written snapshot plus the journal delta since the last tick are serialized
  into one length-framed JSON frame and pushed over a TCP/Unix socket by a
  dedicated sender thread.  Between the Reporter and the socket sits a
  BOUNDED drop-oldest outbox — a slow or dead aggregator can never block or
  wedge the Reporter; it only costs frames (counted in ``frames_dropped``,
  surfaced as the ``telemetry`` snapshot section and the
  ``windflow_telemetry_*`` gauges).

- :class:`FleetAggregator` (daemon side of ``scripts/wf_fleet.py serve``)
  accepts any number of host streams — join/leave/torn-frame/restart
  tolerant, hosts keyed by the tag each frame carries — and maintains a
  rolling fleet snapshot through the existing
  ``device_health.merge_snapshots`` fold.  Fleet-level SLO specs are
  evaluated over the MERGED view by :class:`FleetSLOEngine` (the PR 15
  engine's burn math unchanged; ``merge_snapshots``' worst-state-wins SLO
  fold supplies the per-host context), and a fleet PAGE captures ONE
  manifest-committed incident bundle whose extra ``correlation.json``
  correlates the same-window per-host pages and references their own bundle
  paths.  The aggregator writes ``snapshot.json`` / ``snapshots.jsonl`` /
  ``events.jsonl`` / ``metrics.prom`` in the exact schema the Reporter
  emits, so ``wf_slo.py`` / ``wf_health.py`` / ``wf_state.py`` /
  ``wf_top.py`` work on an aggregator directory unchanged.

Wire framing: ``b"WFT1 " + 8 hex digits (payload length) + b"\\n" + payload
+ b"\\n"`` where the payload is one UTF-8 JSON object.  The magic prefix is
the resync point — a reader that lands mid-stream (host restart, torn send)
scans forward to the next magic and counts the loss in ``frames_torn``
instead of wedging.

Off by default behind ``MonitoringConfig.telemetry`` / ``WF_TELEMETRY``;
host-side Reporter-thread work only — compiled programs, operator state,
and the perf-gate pins are byte-for-byte unchanged either way
(``tests/test_fleet.py`` pins four-driver result identity and HLO
identity).  Stdlib-only and loadable by file path (the ``slo.py`` /
``device_health.py`` convention), so the aggregator and dashboards run on
boxes without JAX installed.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from . import device_health as _device_health
from . import journal as _journal
from . import slo as _slo

# --------------------------------------------------------------- wire format

#: frame magic — the resync point for readers that land mid-stream
MAGIC = b"WFT1 "
_LEN_DIGITS = 8
_HEADER_LEN = len(MAGIC) + _LEN_DIGITS + 1
#: hard per-frame cap: a corrupt length field must not make the decoder
#: buffer gigabytes waiting for a frame that never completes
MAX_FRAME_BYTES = 64 << 20
#: per-tick cap on the journal delta an agent ships (a journal burst —
#: restart storm, chatty tracing — degrades to a gap, never a huge frame)
_MAX_JOURNAL_DELTA = 1 << 20


def encode_frame(obj: dict) -> bytes:
    """One length-framed JSON frame (see the module docstring's grammar)."""
    payload = json.dumps(obj, default=str).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame payload {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    return MAGIC + b"%0*x" % (_LEN_DIGITS, len(payload)) + b"\n" \
        + payload + b"\n"


class FrameDecoder:
    """Incremental frame parser, torn-input tolerant.

    ``feed(data)`` returns the complete frames decoded so far; bytes that do
    not parse (mid-stream join, torn send, corrupt length, bad JSON) are
    skipped to the next ``MAGIC`` and counted in ``frames_torn`` — the
    stream self-heals at the next intact frame."""

    def __init__(self):
        self._buf = bytearray()
        self.frames_decoded = 0
        self.frames_torn = 0

    def feed(self, data: bytes) -> List[dict]:
        self._buf += data
        out: List[dict] = []
        while True:
            i = self._buf.find(MAGIC)
            if i < 0:
                # no magic in the buffer: keep only a possible magic PREFIX
                # at the tail, drop the rest as torn noise
                keep = len(MAGIC) - 1
                if len(self._buf) > keep:
                    del self._buf[:len(self._buf) - keep]
                    self.frames_torn += 1
                return out
            if i > 0:
                del self._buf[:i]          # resync: skip torn bytes
                self.frames_torn += 1
            if len(self._buf) < _HEADER_LEN:
                return out                 # header still in flight
            hexlen = self._buf[len(MAGIC):len(MAGIC) + _LEN_DIGITS]
            try:
                n = int(bytes(hexlen), 16)
            except ValueError:
                n = -1
            if (n < 0 or n > MAX_FRAME_BYTES
                    or self._buf[_HEADER_LEN - 1:_HEADER_LEN] != b"\n"):
                del self._buf[:len(MAGIC)]  # corrupt header: resync past it
                self.frames_torn += 1
                continue
            if len(self._buf) < _HEADER_LEN + n + 1:
                return out                 # payload still in flight
            payload = bytes(self._buf[_HEADER_LEN:_HEADER_LEN + n])
            trailer = self._buf[_HEADER_LEN + n:_HEADER_LEN + n + 1]
            if trailer != b"\n":
                del self._buf[:len(MAGIC)]  # length lied: resync
                self.frames_torn += 1
                continue
            del self._buf[:_HEADER_LEN + n + 1]
            try:
                obj = json.loads(payload)
            except ValueError:
                self.frames_torn += 1
                continue
            self.frames_decoded += 1
            out.append(obj)


def parse_endpoint(endpoint: str) -> Tuple[str, ...]:
    """Parse a telemetry endpoint string into ``("tcp", host, port)`` or
    ``("unix", path)``.

    Accepted forms: ``tcp://HOST:PORT``, bare ``HOST:PORT``, and
    ``unix://PATH`` / ``unix:PATH``.  Raises ``ValueError`` on anything
    else — the validator reports an unparseable configured endpoint as
    WF117 before the run."""
    s = str(endpoint or "").strip()
    if not s:
        raise ValueError("empty telemetry endpoint (expected tcp://HOST:PORT"
                         ", HOST:PORT, or unix://PATH)")
    if s.startswith("unix://"):
        path = s[len("unix://"):]
    elif s.startswith("unix:"):
        path = s[len("unix:"):]
    else:
        path = None
    if path is not None:
        if not path:
            raise ValueError(f"unix endpoint {endpoint!r} has an empty path")
        return ("unix", path)
    if s.startswith("tcp://"):
        s = s[len("tcp://"):]
    host, sep, port_s = s.rpartition(":")
    if not sep or not host:
        raise ValueError(f"unparseable telemetry endpoint {endpoint!r} "
                         f"(expected tcp://HOST:PORT, HOST:PORT, or "
                         f"unix://PATH)")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(f"telemetry endpoint {endpoint!r}: port {port_s!r} "
                         f"is not an integer") from None
    if not 0 <= port <= 65535:
        raise ValueError(f"telemetry endpoint {endpoint!r}: port {port} "
                         f"out of range")
    return ("tcp", host.strip("[]"), port)


def _connect(parsed: Tuple[str, ...], timeout: float) -> socket.socket:
    if parsed[0] == "unix":
        sk = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sk.settimeout(timeout)
        sk.connect(parsed[1])
    else:
        sk = socket.create_connection((parsed[1], parsed[2]),
                                      timeout=timeout)
    sk.settimeout(timeout)
    return sk


def _atomic_write(path: str, data: str) -> None:
    """tmp + flush + fsync + rename — readers never observe a torn file
    (the reporter.py/slo.py discipline, duplicated so this module stays
    loadable by file path without the package)."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass


# ------------------------------------------------------------ host-side agent

class TelemetryAgent:
    """Host side of the telemetry plane: a bounded non-blocking bridge from
    the Reporter tick to the aggregator socket.

    ``offer(snap)`` is called by the Reporter thread right after it wrote
    the tick's artifacts; it assembles one frame (snapshot + journal delta +
    incident-bundle references) and appends it to a ``deque(maxlen=outbox)``
    — a full outbox silently evicts the OLDEST frame (counted in
    ``frames_dropped``), so the Reporter's cadence is independent of the
    aggregator's health by construction.  A daemon sender thread drains the
    outbox, reconnecting with capped backoff; connect/loss transitions are
    journaled (``telemetry_connect`` / ``telemetry_lost``).

    Constructor raises ``ValueError`` on a missing/unparseable endpoint or
    an ``outbox < 1`` — loudly at Monitor construction, the SLO-engine
    convention; ``validate()`` reports the same problems as WF117 before
    the run."""

    def __init__(self, endpoint: str, host: str,
                 out_dir: Optional[str] = None, outbox: int = 64,
                 journal_path: Optional[str] = None,
                 journal: Optional[_journal.EventJournal] = None,
                 connect_timeout_s: float = 2.0,
                 reconnect_max_s: float = 2.0):
        self.parsed = parse_endpoint(endpoint)   # ValueError -> WF117
        if int(outbox) < 1:
            raise ValueError(f"telemetry_outbox/WF_TELEMETRY_OUTBOX must be "
                             f">= 1, got {outbox} (the validator reports "
                             f"this as WF117 before the run)")
        self.endpoint = str(endpoint)
        self.host = str(host)
        self.out_dir = out_dir
        self.connect_timeout_s = float(connect_timeout_s)
        self.reconnect_max_s = float(reconnect_max_s)
        self._journal_path = journal_path
        self._journal_off = 0                 # reporter-thread only
        self._journal = journal
        self._seq = 0                         # reporter-thread only
        self._lock = threading.Lock()
        self._outbox: Deque[dict] = collections.deque(maxlen=int(outbox))
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None  # wf-lint: single-writer[driver, telemetry]
        # counters below are guarded by _lock (written on both the reporter
        # and the sender thread, read by stats())
        self._frames_sent = 0
        self._frames_dropped = 0
        self._connects = 0
        self._connected = False
        self._thread: Optional[threading.Thread] = None

    # -- reporter-thread side ---------------------------------------------

    def offer(self, snap: dict) -> None:
        """Enqueue one tick's frame.  NEVER blocks: the only synchronized
        work is a deque append under an uncontended lock."""
        frame = {"kind": "snap", "host": self.host, "seq": self._seq + 1,
                 "mon_dir": self.out_dir, "snap": snap,
                 "journal": self._read_journal_delta(),
                 "incidents": self._incident_refs()}
        self._seq += 1
        with self._lock:
            if len(self._outbox) == self._outbox.maxlen:
                self._frames_dropped += 1     # deque drops the oldest
            self._outbox.append(frame)
        self._wake.set()

    def _read_journal_delta(self) -> List[dict]:
        """New COMPLETE journal lines since the last tick (file-offset
        tailing; a torn in-flight append waits for the next tick — the
        loader convention).  Bounded per tick so a journal burst degrades
        to a gap, never a huge frame."""
        path = self._journal_path
        if not path:
            return []
        try:
            size = os.path.getsize(path)
            if size < self._journal_off:      # rotation/restart: start over
                self._journal_off = 0
            with open(path, "rb") as f:
                f.seek(self._journal_off)
                data = f.read(_MAX_JOURNAL_DELTA)
        except OSError:
            return []
        end = data.rfind(b"\n")
        if end < 0:
            return []
        self._journal_off += end + 1
        out = []
        for line in data[:end + 1].splitlines():
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out

    def _incident_refs(self) -> Optional[List[str]]:
        """This host's committed incident-bundle paths — shipped with every
        frame so the aggregator can reference them from a fleet incident's
        ``correlation.json`` without filesystem access to the host."""
        if not self.out_dir:
            return None
        d = os.path.join(self.out_dir, "incidents")
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return None
        return [os.path.join(d, n) for n in names]

    # -- sender-thread side ------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # wf-lint: thread-role[telemetry]
            target=self._run, name=f"wf-telemetry-{self.host}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        backoff = 0.05
        while True:
            frame = self._pop()
            if frame is None:
                if self._stop.is_set():
                    return
                self._wake.wait(0.2)
                self._wake.clear()
                continue
            if not self._ensure_connected():
                self._requeue(frame)
                if self._stop.is_set():
                    return                # dead aggregator at close: give up
                self._stop.wait(backoff)
                backoff = min(self.reconnect_max_s, backoff * 2)
                continue
            backoff = 0.05
            try:
                self._sock.sendall(encode_frame(frame))
                with self._lock:
                    self._frames_sent += 1
            except (OSError, ValueError):
                self._drop_socket()
                self._requeue(frame)

    def _pop(self) -> Optional[dict]:
        with self._lock:
            return self._outbox.popleft() if self._outbox else None

    def _requeue(self, frame: dict) -> None:
        with self._lock:
            if len(self._outbox) == self._outbox.maxlen:
                self._frames_dropped += 1   # outbox refilled meanwhile
            else:
                self._outbox.appendleft(frame)

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        try:
            self._sock = _connect(self.parsed, self.connect_timeout_s)
        except OSError:
            return False
        with self._lock:
            self._connects += 1
            self._connected = True
        if self._journal is not None:
            self._journal.event("telemetry_connect", host=self.host,
                                endpoint=self.endpoint)
        return True

    def _drop_socket(self) -> None:
        sk, self._sock = self._sock, None
        if sk is not None:
            try:
                sk.close()
            except OSError:
                pass
        with self._lock:
            was = self._connected
            self._connected = False
        if was and self._journal is not None:
            self._journal.event("telemetry_lost", host=self.host,
                                endpoint=self.endpoint)

    def stats(self) -> dict:
        """The ``telemetry`` snapshot section / ``windflow_telemetry_*``
        gauges (names.py::TELEMETRY_GAUGES lockstep — keep in sync)."""
        with self._lock:
            return {"frames_sent": self._frames_sent,
                    "frames_dropped": self._frames_dropped,
                    "reconnects": max(0, self._connects - 1),
                    "outbox_depth": len(self._outbox),
                    "connected": 1 if self._connected else 0}

    def close(self, flush_s: float = 1.0) -> None:
        """Stop the sender, draining the outbox for at most ``flush_s``
        (best-effort: a dead aggregator must not delay run teardown)."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=max(0.0, float(flush_s)))
        self._drop_socket()


# ----------------------------------------------------------- fleet SLO engine

class FleetSLOEngine(_slo.SLOEngine):
    """The PR 15 burn-rate engine evaluated over the MERGED fleet snapshot.

    Burn math, state machine, rate limiting, and bundle commit discipline
    are inherited unchanged; the only addition is ``correlation.json`` in
    every fleet incident bundle — which hosts paged in the same window,
    with their own monitoring dirs and committed bundle paths, so a fleet
    page fans out to the per-host forensics in one hop."""

    def __init__(self, specs, out_dir, host_forensics:
                 Callable[[], List[dict]], **kw):
        super().__init__(specs, out_dir, **kw)
        self._host_forensics = host_forensics

    def _extra_bundle_files(self, st, snap: dict) -> dict:
        # the merged HOST fold (worst_host/pages_by_host), not the fleet
        # engine's own rows — by capture time snap["slo"] holds the latter
        row = (self._incoming_slo or snap.get("slo")
               or {}).get(st.spec.name) or {}
        pages_by_host = row.get("pages_by_host") or {}
        hosts = []
        for h in self._host_forensics():
            hrow = (h.get("slo") or {}).get(st.spec.name) or {}
            burn = hrow.get("burn_fast")
            hosts.append({
                "host": h.get("host"),
                "mon_dir": h.get("mon_dir"),
                "state": hrow.get("state"),
                "burn_fast": burn,
                "pages": hrow.get("pages", 0),
                "bundles": h.get("incidents") or [],
                # correlated = this host is burning on the same SLO in the
                # current window — the fleet page's cause.  Its own STICKY
                # page state lags by up to a frame (the snapshot carrying
                # the transition arrives after the one whose burn caused
                # it), so a host already at page-rate burn or in WARN
                # counts too; healthy hosts sit at state "ok"/burn 0.
                "correlated": bool(
                    hrow.get("state") in (_slo.STATE_PAGE, _slo.STATE_WARN)
                    or pages_by_host.get(h.get("host"))
                    or (burn is not None
                        and burn >= float(st.spec.page_burn))),
            })
        return {"correlation.json": {
            "fleet_slo": st.spec.name, "signal": st.spec.signal,
            "tick": self._tick, "worst_host": row.get("worst_host"),
            "pages_by_host": pages_by_host, "hosts": hosts,
        }}


# --------------------------------------------------------------- aggregator

#: the ``fleet`` snapshot section / ``windflow_fleet_*`` gauges
#: (names.py::FLEET_GAUGES lockstep — keep in sync)
_FLEET_HELP = {
    "hosts_connected": "hosts with a live telemetry stream right now",
    "hosts_seen": "distinct host tags seen since the aggregator started",
    "frames_received": "telemetry frames decoded across all hosts",
    "frames_torn": "wire bytes lost to torn/corrupt frames (resync'd)",
    "ticks": "fleet merge ticks emitted",
}


class FleetAggregator:
    """Accepts host telemetry streams and maintains the rolling fleet view.

    One fleet tick = one ``merge_snapshots`` fold over every host's latest
    snapshot, SLO-judged and written to ``out_dir`` in the Reporter's exact
    artifact schema.  A tick is emitted as soon as every CONNECTED host has
    delivered a fresh snapshot since the last tick (round-complete — the
    fleet tick rate follows the slowest live host), or after
    ``max_skew_s`` with at least one fresh snapshot (straggler timeout, so
    one wedged host cannot freeze the fleet view).  Host journal deltas are
    re-emitted host-tagged into the fleet ``events.jsonl``.

    Join/leave/restart tolerant: hosts are keyed by the tag their frames
    carry; a reconnecting host resumes its slot, and a departed host's last
    snapshot stays in the merged view (its absence is visible via
    ``fleet.hosts_connected`` vs ``merged_from``)."""

    def __init__(self, listen: str, out_dir: str, specs=None,
                 max_skew_s: float = 1.0, cooldown_s: float = 60.0,
                 max_incidents: int = 8, snapshot_keep: Optional[int] = None):
        self.parsed = parse_endpoint(listen)
        self.out_dir = out_dir
        self.max_skew_s = float(max_skew_s)
        self.snapshot_keep = (None if snapshot_keep is None
                              else max(1, int(snapshot_keep)))
        os.makedirs(out_dir, exist_ok=True)
        events_path = os.path.join(out_dir, "events.jsonl")
        self._journal = _journal.EventJournal(events_path)
        self.engine: Optional[FleetSLOEngine] = None
        specs = _slo.resolve_specs(specs) if specs is not None else None
        if specs:
            self.engine = FleetSLOEngine(
                specs, out_dir, self._host_forensics_locked,
                cooldown_s=cooldown_s, max_incidents=max_incidents,
                journal_path=events_path)
            # fleet transitions go to the fleet journal, never the
            # process-global active journal (this process may also be a host)
            self.engine.journal_sink = self._journal
        self._lock = threading.Lock()
        #: per-host state: {tag: {snap, seq, mon_dir, incidents, connected,
        #: fresh, last_rx}} — guarded by _lock
        self._hosts: Dict[str, dict] = {}  # wf-lint: guarded-by[_lock]
        self._frames_received = 0
        self._frames_torn = 0
        self._ticks = 0
        self._jsonl_lines = 0
        self._first_fresh_t: Optional[float] = None
        self._started = time.monotonic()  # wf-lint: allow[wall-clock] timing-only: uptime display
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self.address: Optional[Tuple[str, ...]] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.parsed[0] == "unix":
            path = self.parsed[1]
            try:
                os.unlink(path)              # stale socket from a dead serve
            except OSError:
                pass
            sk = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sk.bind(path)
            self.address = ("unix", path)
        else:
            sk = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sk.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sk.bind((self.parsed[1], self.parsed[2]))
            self.address = ("tcp",) + sk.getsockname()[:2]
        sk.listen(64)
        self._listener = sk
        for target, name in ((self._accept_loop, "wf-fleet-accept"),
                             (self._ticker, "wf-fleet-ticker")):
            t = threading.Thread(  # wf-lint: thread-role[telemetry]
                target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    @property
    def endpoint(self) -> str:
        """The BOUND endpoint as a client string (resolves port 0)."""
        a = self.address or self.parsed
        return f"unix://{a[1]}" if a[0] == "unix" else f"tcp://{a[1]}:{a[2]}"

    def stats(self) -> dict:
        """The fleet counters (the ``_FLEET_HELP`` /
        ``names.FLEET_GAUGES`` set) — the same numbers every fleet
        snapshot carries under ``snap["fleet"]``."""
        with self._lock:
            return {
                "hosts_connected": sum(1 for h in self._hosts.values()
                                       if h["connected"]),
                "hosts_seen": len(self._hosts),
                "frames_received": self._frames_received,
                "frames_torn": self._frames_torn,
                "ticks": self._ticks,
            }

    def stop(self) -> None:
        self._stop.set()
        lst, self._listener = self._listener, None
        if lst is not None:
            try:
                lst.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)
        with self._lock:
            if any(h["fresh"] for h in self._hosts.values()):
                self._emit_locked()          # final partial round
        if self.parsed[0] == "unix":
            try:
                os.unlink(self.parsed[1])
            except OSError:
                pass
        self._journal.close()

    # -- socket side -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return                       # listener closed by stop()
            t = threading.Thread(  # wf-lint: thread-role[telemetry]
                target=self._reader, args=(conn,),
                name="wf-fleet-reader", daemon=True)
            t.start()
            self._threads.append(t)

    def _reader(self, conn: socket.socket) -> None:
        dec = FrameDecoder()
        tag: Optional[str] = None
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:
                    break                    # peer EOF
                for frame in dec.feed(data):
                    tag = self._on_frame(frame, tag)
                if dec.frames_torn:
                    with self._lock:
                        self._frames_torn += dec.frames_torn
                    dec.frames_torn = 0
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if tag is not None:
                with self._lock:
                    h = self._hosts.get(tag)
                    if h is not None:
                        h["connected"] = False
                self._journal.event("fleet_host_leave", host=tag)

    def _on_frame(self, frame: dict, tag: Optional[str]) -> Optional[str]:
        host = frame.get("host")
        if not isinstance(host, str) or frame.get("kind") != "snap":
            with self._lock:
                self._frames_torn += 1       # structurally valid JSON,
            return tag                       # semantically not a frame
        joined = False
        with self._lock:
            h = self._hosts.get(host)
            if h is None:
                h = self._hosts[host] = {"snap": None, "seq": -1,
                                         "mon_dir": None, "incidents": [],
                                         "connected": False, "fresh": False,
                                         "last_rx": 0.0}
                joined = True
            h["connected"] = True
            h["last_rx"] = time.monotonic()  # wf-lint: allow[wall-clock] timing-only: staleness display
            seq = frame.get("seq")
            if isinstance(seq, int):
                h["seq"] = seq               # informational (restart shows
            if frame.get("snap") is not None:  # as a seq reset in the logs)
                h["snap"] = frame["snap"]
                h["fresh"] = True
                if self._first_fresh_t is None:
                    self._first_fresh_t = time.monotonic()  # wf-lint: allow[wall-clock] timing-only: skew-gate cadence
            h["mon_dir"] = frame.get("mon_dir") or h["mon_dir"]
            if frame.get("incidents"):
                h["incidents"] = frame["incidents"]
            self._frames_received += 1
            round_complete = all(st["fresh"] for st in self._hosts.values()
                                 if st["connected"])
        if joined:
            self._journal.event("fleet_host_join", host=host,
                                mon_dir=frame.get("mon_dir"))
        for rec in frame.get("journal") or []:
            if not isinstance(rec, dict):
                continue
            fields = {k: v for k, v in rec.items()
                      if k not in ("event", "name", "t", "wall", "host")}
            self._journal.event(str(rec.get("event", "?")), host=host,
                                src_wall=rec.get("wall"), **fields)
        if round_complete:
            with self._lock:
                if any(st["fresh"] for st in self._hosts.values()):
                    self._emit_locked()
        return host

    def _ticker(self) -> None:
        """Straggler timeout: a round that stays incomplete for
        ``max_skew_s`` is emitted with whatever is fresh — one wedged or
        departed host cannot freeze the fleet view."""
        poll = max(0.05, self.max_skew_s / 4.0)
        while not self._stop.wait(poll):
            with self._lock:
                t0 = self._first_fresh_t
                if (t0 is not None
                        and time.monotonic() - t0 >= self.max_skew_s):  # wf-lint: allow[wall-clock] timing-only: emit cadence
                    self._emit_locked()

    # -- fleet tick --------------------------------------------------------

    def _host_forensics_locked(self) -> List[dict]:
        """Per-host correlation context for FleetSLOEngine — called from
        ``engine.observe`` INSIDE ``_emit_locked``, so ``_lock`` is already
        held."""
        out = []
        # _lock held by caller (see docstring)
        for tag in sorted(self._hosts):      # wf-lint: allow[unguarded]
            h = self._hosts[tag]             # wf-lint: allow[unguarded]
            out.append({"host": tag, "mon_dir": h["mon_dir"],
                        "incidents": h["incidents"],
                        "slo": (h["snap"] or {}).get("slo")})
        return out

    def _emit_locked(self) -> None:
        # _locked suffix = caller (the tick emitters) already holds _lock
        tags = [t for t in sorted(self._hosts)       # wf-lint: allow[unguarded]
                if self._hosts[t]["snap"] is not None]  # wf-lint: allow[unguarded]
        if not tags:
            return
        snaps = [self._hosts[t]["snap"] for t in tags]  # wf-lint: allow[unguarded]
        merged = _device_health.merge_snapshots(snaps, hosts=tags)
        # enrich the merge's provenance rows with the streaming-plane
        # facts only the aggregator knows (where each host's own
        # artifacts/bundles live, whether its socket is still up)
        for row in merged.get("hosts", []):
            h = self._hosts.get(row.get("host"))  # wf-lint: allow[unguarded]
            if h is not None:
                row["mon_dir"] = h["mon_dir"]
                row["connected"] = bool(h["connected"])
        merged["wall_time"] = time.time()  # wf-lint: allow[wall-clock] timing-only: report stamp
        merged["uptime_s"] = round(time.monotonic() - self._started, 3)  # wf-lint: allow[wall-clock] timing-only: uptime display
        self._ticks += 1
        merged["fleet"] = {
            "hosts_connected": sum(1 for h in self._hosts.values()  # wf-lint: allow[unguarded]
                                   if h["connected"]),
            "hosts_seen": len(self._hosts),  # wf-lint: allow[unguarded]
            "frames_received": self._frames_received,
            "frames_torn": self._frames_torn,
            "ticks": self._ticks,
        }
        if self.engine is not None:
            try:
                self.engine.observe(merged)
            except Exception as e:  # noqa: BLE001 — a judging bug must not
                merged["slo_error"] = str(e)   # kill the aggregation plane
        for h in self._hosts.values():       # wf-lint: allow[unguarded]
            h["fresh"] = False
        self._first_fresh_t = None
        self._write_artifacts(merged)

    def _write_artifacts(self, merged: dict) -> None:
        data = json.dumps(merged, default=str)
        _atomic_write(os.path.join(self.out_dir, "snapshot.json"), data)
        series = os.path.join(self.out_dir, "snapshots.jsonl")
        with open(series, "a") as f:
            f.write(data + "\n")
        self._jsonl_lines += 1
        keep = self.snapshot_keep
        if keep is not None and self._jsonl_lines >= 2 * keep:
            try:                             # amortized trim, atomic rewrite
                with open(series) as f:
                    lines = f.readlines()[-keep:]
                _atomic_write(series, "".join(lines))
                self._jsonl_lines = len(lines)
            except OSError:
                pass
        _atomic_write(os.path.join(self.out_dir, "metrics.prom"),
                      render_prometheus(merged))


# ------------------------------------------------------ prometheus rendering

def render_prometheus(snap: dict) -> str:
    """Text exposition for a MERGED fleet snapshot — the subset of the
    Reporter's families that survive the fold (fleet/slo gauges, queue
    depths, the merged e2e percentiles); per-operator histograms need the
    live LogHistograms and stay a host-Reporter concern."""
    esc = lambda s: str(s).replace("\\", r"\\").replace('"', r'\"')  # noqa: E731
    g = snap.get("graph", "?")
    lines: List[str] = []
    fleet = snap.get("fleet") or {}
    for name in sorted(_FLEET_HELP):
        if name in fleet:
            lines.append(f"# HELP windflow_fleet_{name} {_FLEET_HELP[name]}")
            lines.append(f"# TYPE windflow_fleet_{name} gauge")
            lines.append(f'windflow_fleet_{name}{{graph="{esc(g)}"}} '
                         f'{fleet[name]}')
    sec = snap.get("slo") or {}
    typed = set()

    def head(name):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE windflow_slo_{name} gauge")

    for slo_name, row in sorted(sec.items()):
        lab = f'graph="{esc(g)}",slo="{esc(slo_name)}"'
        for name in ("burn_fast", "burn_slow", "signal", "target", "pages"):
            v = row.get(name)
            if v is not None:
                head(name)
                lines.append(f'windflow_slo_{name}{{{lab}}} {v}')
        if row.get("code") is not None:
            head("state")
            lines.append(f'windflow_slo_state{{{lab}}} {row["code"]}')
    queues = snap.get("queues") or {}
    if queues:
        lines.append("# TYPE windflow_queue_depth gauge")
        for edge, depth in queues.items():
            lines.append(f'windflow_queue_depth{{graph="{esc(g)}",'
                         f'edge="{esc(edge)}"}} {depth}')
    e2e = snap.get("e2e_latency_us") or {}
    for pct in ("p50", "p95", "p99"):
        if e2e.get(pct) is not None:
            lines.append(f"# TYPE windflow_e2e_latency_{pct}_us gauge")
            lines.append(f'windflow_e2e_latency_{pct}_us'
                         f'{{graph="{esc(g)}"}} {e2e[pct]}')
    return "\n".join(lines) + "\n"
