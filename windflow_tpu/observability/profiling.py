"""Profile-on-page — bounded device-profiler capture for incident bundles.

The PR 15 incident machinery commits host-side forensics (sections, burn
timeline, journal tail, flight-recorder trace) but zero on-device evidence:
a latency PAGE says *that* a tenant is slow, never *which kernels* its time
went to.  This module closes that gap with a bounded ``jax.profiler``
capture window that can fire from PAGE entry (``SLOEngine.profiler``) or be
opened programmatically (:func:`profile_window`).

Discipline:

- **One session guard.**  Every capture goes through the ONE existing
  ``windflow_tpu.stats.xprof_trace`` session latch — never a second latch
  path, never nested: when the guard is held (a user's ``xprof_trace``
  region, a TensorBoard capture), the incident path records a
  ``profile_skipped`` reason into the bundle instead of fighting for the
  profiler, and the programmatic path surfaces the guard's RuntimeError
  naming the holder (the ``tests/test_tracing.py`` pin).
- **Bounded + rate-limited.**  A capture window is ``window_ms`` of wall
  time on the Reporter tick thread, so the validator (WF120) refuses
  windows that reach the reporter interval (a capture that outlives its
  tick would stack).  On top of the engine's own cooldown/max-incidents
  rate limit, :class:`ProfileOnPage` counts its own attempts against
  ``max_captures`` — a re-paging storm profiles the first incidents, then
  records skips.
- **Committed before the manifest.**  The capture lands under
  ``<bundle>/profile/`` and its summary (``profile.json``) joins the
  manifest's ``files`` list — the bundle commit point stays LAST, so a
  committed bundle either carries the capture or says why not.

Stdlib-loadable by file path (the ``slo.py`` convention): ``jax`` and the
``windflow_tpu.stats`` guard are imported inside function bodies only, so
``scripts/wf_profile.py`` can load this module on a box with neither.

Env toggles (off by default, the ``WF_*`` convention; ``''``/``'0'`` = off)::

    WF_PROFILE=1                 # profile-on-page inside incident bundles
    WF_PROFILE_WINDOW_MS=250     # capture window (must stay < reporter tick)
    WF_PROFILE_MAX_CAPTURES=2    # captures per run, on top of the incident
                                 # cooldown/max discipline
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional, Union

#: capture window default — well under the Reporter's minimum interval
#: guardrail relative to the 1 s default tick, and long enough to cover
#: several serving batches on either backend
DEFAULT_WINDOW_MS = 250.0
#: captures per run (attempts, not successes: a backend that refuses must
#: not be retried on every subsequent page)
DEFAULT_MAX_CAPTURES = 2


@dataclasses.dataclass(frozen=True)
class ProfileConfig:
    """Resolved profile-on-page settings (``MonitoringConfig.profile``)."""

    window_ms: float = DEFAULT_WINDOW_MS
    max_captures: int = DEFAULT_MAX_CAPTURES

    def __post_init__(self):
        if float(self.window_ms) <= 0:
            raise ValueError(f"profile window_ms must be > 0, got "
                             f"{self.window_ms}")
        if int(self.max_captures) < 1:
            raise ValueError(f"profile max_captures must be >= 1, got "
                             f"{self.max_captures}")


def resolve_profile(profile: Union[None, bool, ProfileConfig],
                    ) -> Optional[ProfileConfig]:
    """Normalize the ``profile=`` argument (the ``TraceConfig.resolve``
    convention).  ``None`` consults ``WF_PROFILE`` (``''``/``'0'`` = off);
    ``False`` forces off; ``True`` = defaults; a config passes through.
    ``WF_PROFILE_WINDOW_MS`` / ``WF_PROFILE_MAX_CAPTURES`` override either
    way.  Returns None when profiling is off."""
    if profile is False:
        return None
    if isinstance(profile, ProfileConfig):
        cfg = profile
    elif profile is True:
        cfg = ProfileConfig()
    else:                                  # None: env-driven
        env = os.environ.get("WF_PROFILE", "")
        if env in ("", "0"):
            return None
        cfg = ProfileConfig()
    win = os.environ.get("WF_PROFILE_WINDOW_MS", "")
    if win:
        cfg = dataclasses.replace(cfg, window_ms=float(win))
    mx = os.environ.get("WF_PROFILE_MAX_CAPTURES", "")
    if mx:
        cfg = dataclasses.replace(cfg, max_captures=int(mx))
    return cfg


def profile_problems(cfg: Optional[ProfileConfig],
                     slo_on: bool,
                     interval_s: Optional[float]) -> List[str]:
    """The WF120 check surface (shared by ``MonitoringConfig`` construction
    and ``analysis/validate.py``): problems with a resolved profile config
    against the monitoring setup it rides.  Empty when ``cfg`` is None."""
    if cfg is None:
        return []
    probs: List[str] = []
    if not slo_on:
        probs.append(
            "profile-on-page is on but the SLO engine is off — captures "
            "trigger from PAGE entry only, so WF_PROFILE without WF_SLO "
            "(monitoring + at least one SLOSpec) can never fire")
    if interval_s is not None and float(cfg.window_ms) / 1e3 >= float(
            interval_s):
        probs.append(
            f"profile window {cfg.window_ms} ms >= reporter interval "
            f"{float(interval_s) * 1e3:g} ms — the capture runs ON the "
            f"Reporter tick thread, so a window that reaches the interval "
            f"stacks ticks; shrink WF_PROFILE_WINDOW_MS or stretch the "
            f"monitoring interval")
    try:
        import jax  # noqa: F401 — availability probe only
    except Exception as e:  # noqa: BLE001 — any import failure means no jax
        probs.append(
            f"profile-on-page is on but jax is not importable on this box "
            f"({type(e).__name__}: {e}) — every capture would be skipped; "
            f"unset WF_PROFILE where the serving host has no device "
            f"runtime")
    return probs


def profile_window(logdir: str,
                   window_ms: float = DEFAULT_WINDOW_MS) -> dict:
    """One bounded profiler capture: open the ONE ``stats.xprof_trace``
    session, hold it for ``window_ms`` of wall time while the device keeps
    executing whatever the drive loop has in flight, close it, and return
    a summary (``logdir``, ``window_ms``, the files written with sizes).

    Raises the guard's RuntimeError (naming the holder) when a session is
    already active — the programmatic caller decides; the incident path
    (:class:`ProfileOnPage`) converts it into a ``profile_skipped``
    record."""
    from ..stats import xprof_trace  # lazy: jax-bearing module
    window_s = float(window_ms) / 1e3
    t0 = time.perf_counter()  # wf-lint: allow[wall-clock] timing-only: capture window bound
    with xprof_trace(logdir):
        # the window IS a sleep: the profiler samples the device/runtime
        # threads, the capture thread only bounds the session
        while True:
            left = window_s - (time.perf_counter() - t0)  # wf-lint: allow[wall-clock] timing-only: capture window bound
            if left <= 0:
                break
            time.sleep(min(left, 0.01))
    files = []
    for root, _dirs, names in os.walk(logdir):
        for nm in sorted(names):
            p = os.path.join(root, nm)
            try:
                files.append({"name": os.path.relpath(p, logdir),
                              "bytes": os.path.getsize(p)})
            except OSError:
                continue
    return {"logdir": logdir, "window_ms": float(window_ms),
            "files": sorted(files, key=lambda f: f["name"])}


class ProfileOnPage:
    """The ``SLOEngine.profiler`` callable: ``fn(out_dir) -> dict`` run at
    incident-capture time, BEFORE the manifest commits.  Returns either a
    :func:`profile_window` summary or ``{"profile_skipped": reason}`` —
    never raises (forensics must not kill a Reporter tick), and never
    latches anything itself (the one-session-guard satellite: a held
    ``xprof_trace`` is a skip reason, not a second latch)."""

    def __init__(self, config: Optional[ProfileConfig] = None):
        self.config = config or ProfileConfig()
        #: capture attempts so far — single-writer: the Reporter tick
        #: thread is the only caller (SLOEngine.observe -> capture)
        self.captures = 0                 # wf-lint: single-writer[reporter]

    def __call__(self, out_dir: str) -> dict:
        if self.captures >= int(self.config.max_captures):
            return {"profile_skipped":
                    f"max captures reached "
                    f"({int(self.config.max_captures)} per run)"}
        self.captures += 1
        try:
            import jax  # noqa: F401 — availability probe only
        except Exception as e:  # noqa: BLE001 — no jax: record why, move on
            return {"profile_skipped":
                    f"jax unavailable ({type(e).__name__}: {e})"}
        try:
            os.makedirs(out_dir, exist_ok=True)
            return profile_window(out_dir, self.config.window_ms)
        except RuntimeError as e:
            # the session guard (another capture holds the one profiler
            # session) or a backend that cannot profile — both are skip
            # reasons inside a bundle, never a failed tick
            return {"profile_skipped": f"{type(e).__name__}: {e}"}
        except OSError as e:
            return {"profile_skipped": f"OSError: {e}"}


def load_profile(bundle_dir: str) -> Optional[dict]:
    """``profile.json`` of one incident bundle (or None) — the
    ``wf_profile.py`` reader; stdlib only."""
    import json
    path = os.path.join(bundle_dir, "profile.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
