"""Event-time observability primitives: lateness histograms + delay advice.

PR 8 shipped an event-time subsystem (versioned JoinTable, session tables,
interval-join archives, leaderboards) whose health was invisible at runtime:
an operator silently sheds ``tuples_dropped_old`` / ``match_drops`` / overflow
drops and the only artifact is a counter — no record of *how late* the shed
tuples were, on which stream, or what ``delay=`` would have kept them.  This
module is the shared core of that answer:

- **Lateness histogram geometry** (host side, stdlib only): ``NB`` power-of-
  two buckets over observed lateness ``watermark - ts`` in event-time ticks.
  Bucket 0 holds exactly-on-time tuples (lateness 0); bucket ``b >= 1`` holds
  lateness with bit length ``b``, i.e. ``[2**(b-1), 2**b - 1]`` — so a
  reported quantile's upper bound is within 2x of the true sample quantile,
  the ``LogHistogram`` trade made integer-exact for event time.
- :func:`recommend_delay`: reads a histogram and names the smallest
  ``delay=`` (at bucket resolution) covering quantile ``q`` of the observed
  lateness — the number an operator's lateness section puts next to its
  drops, and the number ``scripts/wf_state.py`` renders per operator.
- **Device-side update** (:func:`lateness_update`, lazy ``jax`` import): ONE
  masked ``[C, NB]`` compare-reduce per batch folded into the operator's
  carried state — read back with the existing snapshot-time stats reads, so
  the forensics cost zero extra transfers and zero device work when the
  ``MonitoringConfig.event_time`` toggle is off (the histogram is simply not
  in the state pytree).

This module must stay importable WITHOUT jax at module scope:
``scripts/wf_state.py`` loads it by file path (the ``wf_trace.py`` /
``tracing.py`` convention) to reuse the bucket math on any box the
monitoring artifacts were copied to.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

#: lateness histogram buckets: bucket 0 = lateness 0; bucket b >= 1 =
#: lateness with bit length b (``[2**(b-1), 2**b - 1]`` ticks).  31 is the
#: widest bit length an int32 lateness can have, so 32 buckets are lossless.
NB = 32


def bucket_of(lateness: int) -> int:
    """Bucket index of one observed lateness value (host-side mirror of the
    device one-hot; tests pin the two agree)."""
    lat = max(0, int(lateness))
    return min(lat.bit_length(), NB - 1)


def bucket_upper(i: int) -> int:
    """Inclusive upper bound (ticks) of bucket ``i`` — the delay that covers
    every lateness the bucket can hold."""
    i = int(i)
    return 0 if i <= 0 else (1 << i) - 1


def lateness_quantile(counts: Sequence[int], q: float) -> int:
    """Upper bound (ticks) of the bucket containing quantile ``q`` (0 < q
    <= 1) of the recorded lateness samples; 0 when the histogram is empty."""
    total = sum(int(c) for c in counts)
    if total <= 0:
        return 0
    target = max(1, math.ceil(float(q) * total))
    acc = 0
    for i, c in enumerate(counts):
        acc += int(c)
        if acc >= target:
            return bucket_upper(i)
    return bucket_upper(len(counts) - 1)


def recommend_delay(counts: Sequence[int], q: float = 0.99) -> int:
    """THE delay advice: the smallest ``delay=`` (at bucket resolution —
    within 2x of the exact sample quantile) that covers quantile ``q`` of the
    observed lateness.  An operator run with ``delay >=`` this value would
    have accepted that fraction of its arrivals as on-time; ``q=1.0`` names
    the delay that drives ``tuples_dropped_old`` / overflow drops to zero
    for the recorded stream (the contract ``tests/test_event_time.py``
    pins end to end)."""
    return lateness_quantile(counts, q)


def summarize(counts: Sequence[int],
              q_recommend: float = 0.99) -> Dict[str, object]:
    """Snapshot-ready summary of one lateness histogram: raw bucket counts
    (so ``wf_state.py`` can re-quantile at any q), p50/p95/p99 upper bounds,
    max-bucket bound, and the default delay recommendation."""
    counts = [int(c) for c in counts]
    total = sum(counts)
    out: Dict[str, object] = {"counts": counts, "total": total}
    if total:
        out["p50"] = lateness_quantile(counts, 0.50)
        out["p95"] = lateness_quantile(counts, 0.95)
        out["p99"] = lateness_quantile(counts, 0.99)
        last = max(i for i, c in enumerate(counts) if c)
        out["max"] = bucket_upper(last)
        out["recommend_delay_p99"] = recommend_delay(counts, q_recommend)
    return out


# ------------------------------------------------------------- device side
#
# jax is imported INSIDE the functions below: the module itself must load
# without jax (wf_state.py loads it by path), and the device helpers only
# ever run under an operator's traced ``apply`` with event_time monitoring
# on.


def lateness_init(nb: int = NB):
    """Fresh on-device histogram (i32[nb]) for an operator's state pytree —
    present ONLY when the ``event_time`` toggle resolved on at chain build,
    so the off path's compiled program (and its perf-gate cost pins) carries
    zero extra state."""
    import jax.numpy as jnp
    return jnp.zeros((int(nb),), jnp.int32)


def lateness_update(hist, watermark, ts, mask):
    """Fold one batch's observed lateness into the histogram: ONE masked
    ``[C, NB]`` compare + reduction (no scatter, no gather).  ``watermark``
    is the operator's post-batch event-time frontier (scalar), ``ts`` the
    per-lane event times (i32[C]), ``mask`` the lanes to record (bool[C]).
    The bucket index is the lateness bit length, computed as a threshold
    count — integer-exact, so the host mirror :func:`bucket_of` agrees."""
    import jax.numpy as jnp
    nb = hist.shape[0]
    lat = jnp.maximum(jnp.asarray(watermark, jnp.int32)
                      - ts.astype(jnp.int32), 0)
    # thresholds 2**0 .. 2**(nb-2): count how many are <= lat = bit length
    th = jnp.left_shift(jnp.asarray(1, jnp.int32),
                        jnp.arange(nb - 1, dtype=jnp.int32))
    b = jnp.sum((lat[:, None] >= th[None, :]).astype(jnp.int32), axis=1)
    oh = (b[:, None] == jnp.arange(nb, dtype=jnp.int32)[None, :]) \
        & mask[:, None]
    return hist + jnp.sum(oh.astype(jnp.int32), axis=0)


def read_hist(hist) -> Optional[List[int]]:
    """Host list of bucket counts from a device histogram (snapshot-time
    read; None when the state carries no histogram)."""
    if hist is None:
        return None
    import numpy as np
    return [int(v) for v in np.asarray(hist)]
