"""Structured event journal — JSONL spans with monotonic timestamps.

The runtime's discrete events (checkpoint commits, restores/restarts,
ordering-buffer flushes, EOS propagation, sampled compiled-program launches)
are appended as one JSON object per line, so a round's artifacts carry the
*sequence* of what happened, not just end-state counters. Every record has:

- ``t``: ``time.monotonic()`` at emission — totally ordered within a process;
- ``wall``: ``time.time()`` for cross-process correlation;
- ``event``: the event name;
- spans additionally: ``phase`` (``begin``/``end``), ``span`` (a per-journal
  sequence number pairing begin with end), and on ``end`` a ``dur_s``.

Call sites go through the module-level active journal (:func:`record` /
:func:`span`), which is a no-op costing one attribute load + None check when
monitoring is off — safe in per-batch paths.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional


class EventJournal:
    """Append-only JSONL journal. Thread-safe.

    Flushing: the default (``flush_interval=None``) flushes per event —
    events are checkpoint/EOS-granular, not per-tuple, and a crash must not
    lose the records describing it, so supervised runs keep this mode.
    Tracing-heavy runs (sampled launches, per-batch spans) can opt into
    batched flushing with ``flush_interval=N``: the stream is flushed every N
    events instead of paying a write syscall per record; error-carrying
    records and ``close()`` always flush immediately, so the failure tail is
    never buffered away."""

    def __init__(self, path: str, flush_interval: Optional[int] = None):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.flush_interval = (None if not flush_interval
                               else max(1, int(flush_interval)))
        # line buffering when per-event; block buffering when batched
        self._f = open(path, "a",
                       buffering=(1 if self.flush_interval is None else -1))
        self._lock = threading.Lock()
        self._span_seq = 0
        self._since_flush = 0
        self.events_written = 0

    def event(self, name: str, **fields) -> None:
        rec = {"t": time.monotonic(), "wall": time.time(), "event": name}
        rec.update(fields)
        line = json.dumps(rec, default=str)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self.events_written += 1
            if self.flush_interval is not None:
                self._since_flush += 1
                if (self._since_flush >= self.flush_interval
                        or "error" in rec):
                    self._f.flush()
                    self._since_flush = 0

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """``begin``/``end`` record pair around a block; ``end`` carries the
        measured ``dur_s`` (and ``error`` if the block raised)."""
        with self._lock:
            self._span_seq += 1
            sid = self._span_seq
        t0 = time.monotonic()
        self.event(name, phase="begin", span=sid, **fields)
        try:
            yield sid
        except BaseException as e:
            # the in-span failure overrides any caller-supplied 'error' field
            # (e.g. a restore span opened with the error being recovered FROM)
            # — a dict merge, never a duplicate-kwarg TypeError that would
            # mask the real exception
            self.event(name, phase="end", span=sid,
                       dur_s=round(time.monotonic() - t0, 6),
                       **{**fields, "error": type(e).__name__})
            raise
        self.event(name, phase="end", span=sid,
                   dur_s=round(time.monotonic() - t0, 6), **fields)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


#: process-global active journal (set by the Monitor when monitoring is on).
#: Runtime call sites use the module-level helpers below so a disabled journal
#: costs one None check.
_active: Optional[EventJournal] = None


def set_active(journal: Optional[EventJournal]) -> None:
    global _active
    _active = journal


def get_active() -> Optional[EventJournal]:
    return _active


def record(name: str, **fields) -> None:
    """Emit one event to the active journal; no-op when none is active."""
    j = _active
    if j is not None:
        j.event(name, **fields)


def span(name: str, **fields):
    """Span context manager on the active journal; no-op context when none."""
    j = _active
    if j is not None:
        return j.span(name, **fields)
    return contextlib.nullcontext()


def read_journal(path: str):
    """Parse a journal file back into a list of dicts (tests/tooling)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
