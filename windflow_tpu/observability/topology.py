"""Topology export: the compiled PipeGraph/MultiPipe app-tree as dot + JSON.

The reference dumps the PipeGraph as a graphviz diagram under
``GRAPHVIZ_WINDFLOW`` (``wf/pipegraph.hpp:226-237,1450-1518``). This module is
that dump for the TPU port, extended with live telemetry when a
:class:`~.metrics.MetricsRegistry` snapshot is supplied: per-edge tuple rates
(producer output rate) and — under the threaded driver — SPSC queue depths
(the backpressure signal).

Two graph shapes are supported:

- ``PipeGraph`` (DAG of MultiPipes with split/merge edges + the Application
  Tree legality forest);
- ``Pipeline`` (the linear source → ops → sink slice), exported as a chain.
"""

from __future__ import annotations

from typing import Optional


def _op_info(op, rates: Optional[dict] = None,
             state_bytes: Optional[dict] = None) -> dict:
    info = {
        "name": op.getName(),
        "routing": op.getRoutingMode().name,
        "parallelism": op.getParallelism(),
        "chained": op._chained,
    }
    if rates and op.getName() in rates:
        r = rates[op.getName()]
        info["rate_in_tps"] = r.get("rate_in_tps")
        info["rate_out_tps"] = r.get("rate_out_tps")
    if state_bytes and op.getName() in state_bytes:
        # HBM memory ledger (health monitoring): the operator's state-
        # pytree footprint, so the topology names WHERE device memory sits
        info["state_bytes"] = state_bytes[op.getName()]
    return info


def _rates_by_op(snapshot: Optional[dict]) -> dict:
    if not snapshot:
        return {}
    return {row["name"]: row for row in snapshot.get("operators", [])}


def _app_tree(graph, index) -> list:
    """Serialize the live Application-Tree forest (nodes with
    ``absorbed == False``; ``wf/pipegraph.hpp:64-75``)."""
    def ser(node):
        return {"pipe": index.get(id(node.mp)),
                "children": [ser(c) for c in node.children if not c.absorbed]}
    roots = [n for n in graph._nodes.values()
             if not n.absorbed and n.parent is None]
    return [ser(r) for r in roots]


# ---------------------------------------------------------------- PipeGraph

def graph_topology_json(graph, snapshot: Optional[dict] = None) -> dict:
    """JSON topology of a PipeGraph: per-pipe nodes (source/ops/sink), dataflow
    edges (source/split/merge/sink) annotated with live rates + queue depths,
    and the Application-Tree forest."""
    rates = _rates_by_op(snapshot)
    queues = (snapshot or {}).get("queues", {})
    # per-edge watermark skew (event-time monitoring): the registry computes
    # it over the SAME edge-label enumeration the threaded driver rings use
    skews = ((snapshot or {}).get("event_time") or {}).get("edge_skew_ts",
                                                           {})
    health = (snapshot or {}).get("health") or {}
    state_bytes = health.get("state_bytes") or {}
    pipes = graph._all_pipes()
    index = {id(p): i for i, p in enumerate(pipes)}
    nodes, edges = [], []
    for i, p in enumerate(pipes):
        nodes.append({
            "id": i,
            "source": p.source.getName() if p.source is not None else None,
            "sink": p.sink.getName() if p.sink is not None else None,
            "ops": [_op_info(o, rates, state_bytes) for o in p.ops],
            "compiled": p._chain is not None,
        })

    def edge(src, dst, kind, rate_op=None):
        e = {"from": src, "to": dst, "kind": kind}
        label = f"{src}->{dst}"
        if label in queues:
            e["queue_depth"] = queues[label]
        if label in skews:
            e["watermark_skew_ts"] = skews[label]
        if rate_op is not None and rate_op.getName() in rates:
            e["rate_tps"] = rates[rate_op.getName()].get("rate_out_tps")
        edges.append(e)

    for p in pipes:
        i = index[id(p)]
        last_op = p.ops[-1] if p.ops else None
        for b in p.split_branches:
            edge(i, index[id(b)], "split", last_op)
        for m in p._outputs_to:
            edge(i, index[id(m)], "merge", last_op)
    out = {
        "graph": graph.name,
        "mode": graph.mode.name,
        "batch_size": graph.batch_size,
        "nodes": nodes,
        "edges": edges,
        "app_tree": _app_tree(graph, index),
    }
    if snapshot:
        out["totals"] = snapshot.get("totals")
        out["e2e_latency_us"] = snapshot.get("e2e_latency_us")
        if snapshot.get("event_time"):
            out["event_time"] = snapshot["event_time"]
        if health:
            # the runtime-health summary rides the topology export too:
            # device headroom + the dispatch-bound stages (fusion
            # candidates), so one artifact answers "where is the memory
            # and which edges is the host loop throttling"
            out["health"] = {
                k: health[k] for k in ("devices", "headroom_risk",
                                       "dispatch_bound", "state_bytes")
                if health.get(k)}
    return out


def graph_topology_dot(graph, snapshot: Optional[dict] = None) -> str:
    """Graphviz dump of a PipeGraph (the reference's GRAPHVIZ_WINDFLOW
    diagram), with live per-edge rates / queue depths when a registry snapshot
    is supplied."""
    rates = _rates_by_op(snapshot)
    queues = (snapshot or {}).get("queues", {})
    skews = ((snapshot or {}).get("event_time") or {}).get("edge_skew_ts",
                                                           {})
    pipes = graph._all_pipes()
    index = {id(p): i for i, p in enumerate(pipes)}
    lines = [f'digraph "{graph.name}" {{', "  rankdir=LR;"]

    def op_label(o):
        tag = "" if o._chained else {
            "FORWARD": "", "NONE": "",
        }.get(o.getRoutingMode().name, f" ({o.getRoutingMode().name.lower()})")
        rate = ""
        if o.getName() in rates:
            tps = rates[o.getName()].get("rate_in_tps")
            if tps:
                rate = f"\\n{_fmt_tps(tps)}"
        return f"{o.getName()}{tag}{rate}"

    for i, p in enumerate(pipes):
        ops = " | ".join(op_label(o) for o in p.ops) or "(empty)"
        src = f"{p.source.getName()} -> " if p.source is not None else ""
        snk = f" -> {p.sink.getName()}" if p.sink is not None else ""
        lines.append(f'  mp{i} [shape=record, label="{src}{ops}{snk}"];')

    def edge_attrs(src, dst, kind):
        label = kind
        key = f"{src}->{dst}"
        if key in queues:
            label += f" depth={queues[key]}"
        if key in skews:
            label += f" skew={skews[key]}"
        return f'[label="{label}"]'

    for p in pipes:
        i = index[id(p)]
        for b in p.split_branches:
            j = index[id(b)]
            lines.append(f"  mp{i} -> mp{j} {edge_attrs(i, j, 'split')};")
        for m in p._outputs_to:
            j = index[id(m)]
            lines.append(f"  mp{i} -> mp{j} {edge_attrs(i, j, 'merge')};")
    lines.append("}")
    return "\n".join(lines)


# ----------------------------------------------------------------- Pipeline

def pipeline_topology_json(pipeline, snapshot: Optional[dict] = None) -> dict:
    """Linear Pipeline as a chain topology (source → ops → sink)."""
    rates = _rates_by_op(snapshot)
    state_bytes = ((snapshot or {}).get("health") or {}).get("state_bytes")
    stages = [{"name": pipeline.source.getName(), "kind": "source"}]
    stages += [dict(_op_info(o, rates, state_bytes), kind="operator")
               for o in pipeline.chain.ops]
    if pipeline.sink is not None:
        stages.append({"name": pipeline.sink.getName(), "kind": "sink"})
    out = {"pipeline": True, "batch_size": pipeline.batch_size,
           "stages": stages,
           "edges": [{"from": i, "to": i + 1, "kind": "chain"}
                     for i in range(len(stages) - 1)]}
    if snapshot:
        out["totals"] = snapshot.get("totals")
        out["e2e_latency_us"] = snapshot.get("e2e_latency_us")
        health = snapshot.get("health") or {}
        if health:
            out["health"] = {
                k: health[k] for k in ("devices", "headroom_risk",
                                       "dispatch_bound", "state_bytes")
                if health.get(k)}
    return out


def pipeline_topology_dot(pipeline, snapshot: Optional[dict] = None) -> str:
    rates = _rates_by_op(snapshot)
    names = [pipeline.source.getName()]
    names += [o.getName() for o in pipeline.chain.ops]
    if pipeline.sink is not None:
        names.append(pipeline.sink.getName())
    lines = ['digraph "pipeline" {', "  rankdir=LR;"]
    for i, n in enumerate(names):
        rate = ""
        if n in rates and rates[n].get("rate_in_tps"):
            rate = f"\\n{_fmt_tps(rates[n]['rate_in_tps'])}"
        lines.append(f'  s{i} [label="{n}{rate}"];')
    for i in range(len(names) - 1):
        lines.append(f"  s{i} -> s{i + 1};")
    lines.append("}")
    return "\n".join(lines)


# ----------------------------------------------------------------- dispatch

def topology_json(target, snapshot: Optional[dict] = None) -> dict:
    """Topology JSON for a PipeGraph or a Pipeline (duck-typed dispatch)."""
    if hasattr(target, "_all_pipes"):
        return graph_topology_json(target, snapshot)
    return pipeline_topology_json(target, snapshot)


def topology_dot(target, snapshot: Optional[dict] = None) -> str:
    """Topology graphviz dot for a PipeGraph or a Pipeline."""
    if hasattr(target, "_all_pipes"):
        return graph_topology_dot(target, snapshot)
    return pipeline_topology_dot(target, snapshot)


def _fmt_tps(tps: float) -> str:
    if tps >= 1e6:
        return f"{tps / 1e6:.1f}M t/s"
    if tps >= 1e3:
        return f"{tps / 1e3:.1f}k t/s"
    return f"{tps:.0f} t/s"
