"""Runtime health ledger — HBM memory, compile/retrace, device-time, fleet.

The PR 1/5/9 observability stack watches *streams* (latency, traces, event
time); this module watches the two *resources* the next ROADMAP arc spends —
device memory (tiered million-key state needs an HBM headroom signal to drive
promotion/eviction) and compilation/dispatch cost (whole-graph fusion needs to
know which edges are dispatch-bound and what each executable costs, the
fusion-economics question of arXiv:1305.1183 / the whole-program-offload
premise of arXiv:2306.11686). Four pieces:

- **HBM memory ledger**: per-device ``memory_stats()`` + live-buffer gauges
  (:func:`device_memory`), per-operator state footprints computed from the
  state-pytree shapes (``CompiledChain.state_footprints``), executable
  footprints from AOT ``memory_analysis`` — all folded into the metrics
  snapshot's ``health`` section and the ``windflow_hbm_headroom_bytes``
  Prometheus gauge.
- **Compile/retrace ledger** (:class:`HealthLedger`): every trace of a
  ``CompiledChain`` step/scan program is journaled (``compile`` events with
  cause, cache key, compile duration, AOT cost-analysis flops/bytes), with an
  unexpected-retrace detector — a re-trace under an already-traced cache key
  means a warm executable was silently recompiled (the live complement of the
  WF102 weak-type and WF109 stale-impl diagnostics) and raises a counter plus
  a ``retrace_unexpected`` journal event.
- **Device-time attribution**: the sampled ``block_until_ready`` points in
  ``CompiledChain.push``/``push_many`` split each sample into host-dispatch
  time vs device time per stage label; the per-stage ratio is the
  *dispatch-bound classifier* that names fusion candidates for whole-graph
  single-dispatch (ROADMAP item 2).
- **Fleet federation** (:func:`merge_snapshots`): N per-host snapshots merge
  into one fleet view — counters summed, watermark frontier min'd, occupancy/
  pressure max'd, per-host provenance kept — consumed by ``scripts/
  wf_health.py`` and ``wf_state.py --merge`` ahead of the multi-host arc.

Everything is off by default behind ``MonitoringConfig.health``
(``WF_MONITORING_HEALTH``, the established ``kwarg=``/``WF_*`` convention);
the off path costs one module-attribute load + ``None`` check per call site
and leaves compiled programs byte-for-byte unchanged (the ledger hooks inside
jitted step bodies are host-side Python that executes at TRACE time only and
contributes no equations to the program).

This module must stay importable WITHOUT jax at module scope:
``scripts/wf_health.py`` / ``wf_state.py`` / ``wf_trace.py`` load it by file
path (the ``event_time.py`` convention) to reuse the snapshot loaders and the
fleet merge on any box the monitoring artifacts were copied to.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from . import journal as _journal

#: snapshot schema version, stamped by ``MetricsRegistry.snapshot()`` as
#: the top-level ``"schema"`` field.  Seed-era snapshots carry no field and
#: read as version 0.  ``merge_snapshots`` never SILENTLY folds hosts that
#: disagree — a heterogeneous fleet mid-upgrade gets a ``schema_mismatch``
#: section that the loaders/CLIs surface.  Bump when a section's meaning
#: (not mere presence — sections are already optional) changes.
SNAPSHOT_SCHEMA = 1

#: dispatch-bound classifier threshold: a stage whose host-dispatch overhead
#: is at least this fraction of its device time is a fusion candidate (the
#: host loop, not the chip, is its ceiling)
DISPATCH_BOUND_RATIO = 0.5

#: headroom below this fraction of the device limit flags [HEADROOM-RISK]
#: (the wf_state.py OVERFLOW-RISK convention, applied to HBM)
HEADROOM_RISK_FRACTION = 0.2

#: compile-record history kept in memory per ledger (the journal holds the
#: full sequence; this bound only caps the snapshot section)
_COMPILE_LOG_CAP = 256


def _fnv1a32(s: str) -> int:
    h = 2166136261
    for ch in s.encode():
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h


# -------------------------------------------------------------- the ledger


class HealthLedger:
    """Per-run compile/retrace + device-time ledger.

    Lifecycle mirrors the event journal/tracer: the Monitor activates one
    ledger for its run (:func:`set_active`); ``CompiledChain`` reaches it
    through the module-level helpers below (one ``None`` check when off).
    Thread-safe: segment threads of the threaded drivers record concurrently;
    trace notes ride a thread-local pending list because a jitted call traces
    synchronously on its calling thread."""

    def __init__(self, sample_every: int = 1, cost_analysis: bool = True):
        #: record device-time attribution on every Nth *sampled* service
        #: point (the sampled pushes already pay a block_until_ready; this
        #: only subsamples the extra perf_counter pair + dict update)
        self.sample_every = max(1, int(sample_every))
        #: AOT-lower the freshly compiled program once more to read XLA's
        #: cost/memory analysis into the compile journal record (CPU-cheap;
        #: disable for compile-heavy sweeps where the journal row may omit
        #: flops/bytes)
        self.cost_analysis = bool(cost_analysis)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.traces = 0                  # every note_trace (compile events)
        self.retraces = 0                # re-trace of a known (stage, kind)
        #                                  under a NEW shape/dtype signature
        #                                  (capacity switch, weak-type drift)
        self.retraces_unexpected = 0     # re-trace under an ALREADY-TRACED
        #                                  signature: a warm executable was
        #                                  silently recompiled
        self.compile_s_total = 0.0
        self.kernel_resolves = 0
        # (label, from_op, kind) -> {sig: traces seen}
        self._sigs: Dict[Tuple[str, int, str], Dict[str, int]] = {}
        self._compile_log: List[dict] = []
        # cache_key -> executable footprint/cost record
        self.executables: Dict[str, dict] = {}
        # stage label -> [device_s, dispatch_s, samples]
        self._service: Dict[str, List[float]] = {}
        self._svc_seen = 0

    # -- cause tracking ----------------------------------------------------

    def set_cause(self, cause: str) -> None:
        """Default cause for compiles noted on this thread (``push`` /
        ``push_many`` / ``warm`` / ``warm_scan``); a :func:`cause` context
        override (``autotune_prewarm``) wins."""
        self._tls.cause = cause

    def _current_cause(self) -> str:
        override = getattr(_CAUSE_TLS, "override", None)
        return override or getattr(self._tls, "cause", "push")

    # -- trace notes (fire at jit TRACE time, inside the step bodies) ------

    def suppressed(self) -> bool:
        return bool(getattr(self._tls, "suppress", 0))

    def _suppress(self, on: bool) -> None:
        self._tls.suppress = getattr(self._tls, "suppress", 0) \
            + (1 if on else -1)

    def note_trace(self, label: str, from_op: int, kind: str, sig: str,
                   capacity: Optional[int] = None,
                   k: Optional[int] = None) -> None:
        """One jit trace of a chain step/scan program observed.  Classifies
        it (fresh compile / shape retrace / unexpected same-signature
        retrace), journals the detector event, and parks a pending record
        for the caller to finish with duration + AOT cost once the traced
        call returns (``commit_pending``)."""
        if self.suppressed():
            return
        key = (label, int(from_op), kind)
        cache_key = f"{_fnv1a32('/'.join((label, str(from_op), kind, sig))):08x}"
        with self._lock:
            self.traces += 1
            seen = self._sigs.setdefault(key, {})
            unexpected = sig in seen
            retrace = bool(seen) and not unexpected
            seen[sig] = seen.get(sig, 0) + 1
            if unexpected:
                self.retraces_unexpected += 1
            elif retrace:
                self.retraces += 1
        rec = {"label": label, "from_op": int(from_op), "kind": kind,
               "cache_key": cache_key, "cause": self._current_cause(),
               "retrace": retrace, "unexpected": unexpected}
        if capacity is not None:
            rec["capacity"] = int(capacity)
        if k is not None and int(k) > 1:
            rec["k"] = int(k)
        if unexpected:
            # the detector event fires immediately (the compile record
            # follows once the call returns with its duration): a warm
            # executable re-traced under an identical signature — jit-cache
            # eviction or an explicit clear, never a shape change
            _journal.record("retrace_unexpected", **rec)
        pending = getattr(self._tls, "pending", None)
        if pending is None:
            pending = self._tls.pending = []
        pending.append(rec)

    def has_pending(self) -> bool:
        """Whether THIS invocation traced/compiled (pending notes parked on
        the calling thread) — the device-time sampler consults it so a
        compile's trace+XLA time is never charged to ``dispatch_ms`` (which
        would permanently mis-flag the stage as dispatch-bound; the sums
        never decay)."""
        return bool(getattr(self._tls, "pending", None))

    def take_pending(self) -> List[dict]:
        out = getattr(self._tls, "pending", None)
        if not out:
            return []
        self._tls.pending = []
        return out

    def clear_pending(self) -> None:
        """Drop pending trace notes on this thread — the supervised restore
        path calls this so a step that faulted mid-compile cannot charge its
        abandoned trace's duration to the next successful push."""
        self._tls.pending = []

    def commit_pending(self, duration_s: float, cost: Optional[dict] = None,
                       op: str = "",
                       notes: Optional[List[dict]] = None) -> None:
        """Finish the pending trace notes of this thread (or the ``notes``
        a caller already took, to compute cost in between): journal one
        ``compile`` event per note (cause, cache key, duration, AOT
        flops/bytes + executable footprint when available) and fold the
        executable record into the snapshot section."""
        notes = self.take_pending() if notes is None else notes
        if not notes:
            return
        dur = float(duration_s) / len(notes)
        for rec in notes:
            rec = dict(rec)
            rec["compile_s"] = round(dur, 6)
            if op:
                rec["op"] = op
            if cost:
                rec.update(cost)
            with self._lock:
                self.compile_s_total += dur
                self._compile_log.append(rec)
                if len(self._compile_log) > _COMPILE_LOG_CAP:
                    del self._compile_log[0]
                if cost:
                    self.executables[rec["cache_key"]] = {
                        "label": rec["label"], "kind": rec["kind"],
                        "from_op": rec["from_op"], **cost}
            _journal.record("compile", **rec)

    # -- device-time attribution -------------------------------------------

    def service_sample(self) -> bool:
        """Whether THIS sampled service point should also record the
        host-dispatch vs device-time split (every Nth, ``sample_every``)."""
        with self._lock:
            self._svc_seen += 1
            return (self._svc_seen % self.sample_every) == 0

    def note_service(self, label: str, dispatch_s: float,
                     device_s: float) -> None:
        with self._lock:
            acc = self._service.setdefault(label, [0.0, 0.0, 0])
            acc[0] += float(device_s)
            acc[1] += float(dispatch_s)
            acc[2] += 1

    def note_kernel_resolve(self, kernel: str, spec_key: str, impl: str,
                            device: str = "") -> None:
        if self.suppressed():
            # the cost-analysis re-lowering of a just-compiled program
            # re-resolves its kernels; those are not NEW resolutions
            return
        with self._lock:
            self.kernel_resolves += 1
        _journal.record("kernel_resolve", kernel=kernel, spec_key=spec_key,
                        impl=impl, device=device)

    # -- snapshot ----------------------------------------------------------

    def device_time_section(self) -> Dict[str, dict]:
        out = {}
        with self._lock:
            items = [(lb, list(acc)) for lb, acc in self._service.items()]
        for label, (dev, disp, n) in items:
            row = {"device_ms": round(dev * 1e3, 3),
                   "dispatch_ms": round(disp * 1e3, 3), "samples": n}
            if dev > 0:
                row["dispatch_ratio"] = round(disp / dev, 4)
            out[label] = row
        return out

    def snapshot_section(self) -> dict:
        dt = self.device_time_section()
        bound = {label: row["dispatch_ratio"] for label, row in dt.items()
                 if row.get("dispatch_ratio", 0.0) >= DISPATCH_BOUND_RATIO}
        with self._lock:
            sec = {
                "compile": {
                    "compiles": self.traces,
                    "retraces": self.retraces,
                    "retraces_unexpected": self.retraces_unexpected,
                    "compile_s_total": round(self.compile_s_total, 6),
                    "kernel_resolves": self.kernel_resolves,
                },
                "compile_log": list(self._compile_log[-32:]),
                "executables": dict(self.executables),
            }
        if dt:
            sec["device_time"] = dt
        if bound:
            sec["dispatch_bound"] = bound
        return sec


# ------------------------------------------------- process-global active hook

_active: Optional[HealthLedger] = None
_CAUSE_TLS = threading.local()


def set_active(ledger: Optional[HealthLedger]) -> None:
    global _active
    _active = ledger


def get_active() -> Optional[HealthLedger]:
    return _active


class _CauseContext:
    __slots__ = ("name", "prev")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.prev = getattr(_CAUSE_TLS, "override", None)
        _CAUSE_TLS.override = self.name
        return self

    def __exit__(self, *exc):
        _CAUSE_TLS.override = self.prev
        return False


def cause(name: str) -> _CauseContext:
    """Context manager attributing compiles noted inside it to ``name``
    (e.g. ``autotune_prewarm`` around the capacity/K-ladder warm loops) —
    overrides the chain methods' default causes for the duration."""
    return _CauseContext(name)


def note_kernel_resolve(kernel: str, spec_key: str, impl: str,
                        device: str = "") -> None:
    led = _active
    if led is not None:
        led.note_kernel_resolve(kernel, spec_key, impl, device)


def clear_pending() -> None:
    led = _active
    if led is not None:
        led.clear_pending()


# ------------------------------------------------------------ device memory


def device_memory() -> List[dict]:
    """Per-device memory gauges (lazy jax import — monitoring path only):
    ``memory_stats()`` where the backend provides it (TPU/GPU; CPU returns
    None, the row then carries only identity + live-buffer shares) and the
    derived ``headroom_bytes = bytes_limit - bytes_in_use``."""
    try:
        import jax
    except ImportError:                    # artifacts-only box
        return []
    out = []
    for d in jax.local_devices():
        row = {"device": f"{d.platform}:{d.id}",
               "kind": getattr(d, "device_kind", "?")}
        try:
            ms = d.memory_stats()
        except (RuntimeError, NotImplementedError):
            ms = None
        if ms:
            in_use = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit", ms.get("bytes_reservable_limit"))
            if in_use is not None:
                row["bytes_in_use"] = int(in_use)
            if limit:
                row["bytes_limit"] = int(limit)
            if in_use is not None and limit:
                row["headroom_bytes"] = int(limit) - int(in_use)
            if ms.get("peak_bytes_in_use") is not None:
                row["peak_bytes_in_use"] = int(ms["peak_bytes_in_use"])
        out.append(row)
    return out


def live_buffer_stats() -> dict:
    """Process-wide live jax array count + bytes (shape metadata only — no
    device sync)."""
    try:
        import jax
    except ImportError:
        return {}
    count = 0
    total = 0
    for a in jax.live_arrays():
        count += 1
        n = 1
        for dim in getattr(a, "shape", ()):
            n *= dim
        total += n * getattr(getattr(a, "dtype", None), "itemsize", 4)
    return {"live_buffer_count": count, "live_buffer_bytes": total}


def headroom_risks(devices: Sequence[dict]) -> List[str]:
    """Device labels whose headroom sits below ``HEADROOM_RISK_FRACTION`` of
    the limit — the promotion/eviction signal tiered state (ROADMAP 3)
    consumes."""
    out = []
    for row in devices or []:
        head, limit = row.get("headroom_bytes"), row.get("bytes_limit")
        if head is not None and limit:
            if head < HEADROOM_RISK_FRACTION * limit:
                out.append(row.get("device", "?"))
    return out


# ------------------------------------------------- shared snapshot loading
#
# THE one snapshot/journal loader for wf_state.py / wf_trace.py /
# wf_health.py (each previously grew its own copy).  Torn-tolerant: a
# snapshots.jsonl line cut mid-write (host crash between append and flush)
# is skipped, never a crash — and snapshot.json itself is written via
# tmp+os.replace by the Reporter, so a reader can never observe it torn.


def load_snapshots(mon_dir: str):
    """(latest snapshot, full time series) from a monitoring directory.
    Raises FileNotFoundError when neither artifact exists."""
    series = []
    jl = os.path.join(mon_dir, "snapshots.jsonl")
    if os.path.exists(jl):
        with open(jl) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    series.append(json.loads(line))
                except ValueError:
                    # torn tail of an append in progress — drop the line,
                    # keep the parsed prefix (the Reporter's snapshot.json
                    # replace is atomic; the jsonl append is not)
                    continue
    latest = None
    sj = os.path.join(mon_dir, "snapshot.json")
    if os.path.exists(sj):
        try:
            with open(sj) as f:
                latest = json.load(f)
        except ValueError:
            latest = None
    if latest is None and series:
        latest = series[-1]
    if latest is None:
        raise FileNotFoundError(
            f"no snapshot.json / snapshots.jsonl under {mon_dir!r}")
    return latest, series


def load_journal(mon_dir: str) -> List[dict]:
    path = os.path.join(mon_dir, "events.jsonl")
    out = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue               # torn tail, same policy as above
    return out


# --------------------------------------------------------- fleet federation


def _sum_into(dst: dict, src: dict) -> None:
    for k, v in (src or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            dst[k] = dst.get(k, 0) + v


def _max_into(dst: dict, src: dict) -> None:
    for k, v in (src or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            dst[k] = max(dst.get(k, v), v)


#: event-time section keys merged by MAX across hosts (pressure gauges: the
#: fleet view must show the worst host) — everything else numeric in the
#: per-op section is summed (counters) except the watermark family, which
#: takes MIN (the frontier is held by the slowest host)
_ET_MAX_KEYS = ("occupancy_pct", "pending_depth", "l_fill_pct", "r_fill_pct",
                "open_sessions", "oldest_open_age", "lag")
_ET_MIN_KEYS = ("watermark_ts", "fire_frontier_ts")

#: tiered-state sub-section ("tier" in the event-time rows): occupancy /
#: size gauges take MAX (the fleet view shows the worst host), the
#: spill/readmit/compaction movement counters SUM
_TIER_MAX_KEYS = ("hot_pct", "hot_used", "hot_slots", "outbox_slots",
                  "outbox_depth", "cold_keys", "cold_rows",
                  "l_cold_rows", "r_cold_rows")


def _merge_tier_section(dst: dict, src: dict) -> None:
    for k, v in (src or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k in _TIER_MAX_KEYS:
            dst[k] = max(dst.get(k, v), v)
        else:                       # state_spills/readmits/compactions
            dst[k] = dst.get(k, 0) + v


def _merge_et_section(dst: dict, src: dict) -> None:
    for k, v in (src or {}).items():
        if k == "tier" and isinstance(v, dict):
            _merge_tier_section(dst.setdefault("tier", {}), v)
            continue
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k in _ET_MAX_KEYS:
            dst[k] = max(dst.get(k, v), v)
        elif k in _ET_MIN_KEYS:
            dst[k] = min(dst.get(k, v), v)
        else:
            dst[k] = dst.get(k, 0) + v


def merge_snapshots(snaps: Sequence[dict],
                    hosts: Optional[Sequence[str]] = None) -> dict:
    """Fold N per-host snapshots into ONE fleet snapshot: counters summed,
    the watermark frontier min'd, occupancy/pressure gauges max'd, queue
    depths max'd, HBM/health ledgers concatenated/summed, per-host
    provenance kept under ``hosts``.  Latency percentiles cannot be merged
    from summaries — the fleet row keeps the MAX percentile (worst host)
    and the summed sample count, which is the honest conservative read."""
    snaps = [s for s in snaps if s]
    if not snaps:
        raise ValueError("merge_snapshots: no snapshots to merge")
    hosts = list(hosts) if hosts else []
    if len(hosts) < len(snaps):               # pad, never silently truncate
        hosts += [f"host{i}" for i in range(len(hosts), len(snaps))]
    # duplicate host tags (two --merge dirs with the same basename) are
    # disambiguated with a #N suffix, never silently folded into one host's
    # rows — host-tagged sections (shards, pages_by_host, devices) would
    # otherwise collide and drop data
    seen_tags: Dict[str, int] = {}
    for i, h in enumerate(hosts):
        n = seen_tags.get(h, 0) + 1
        seen_tags[h] = n
        if n > 1:
            hosts[i] = f"{h}#{n}"
    out: dict = {
        "graph": "+".join(dict.fromkeys(s.get("graph", "?") for s in snaps)),
        "merged_from": len(snaps),
        "hosts": [{"host": h, "graph": s.get("graph"),
                   "wall_time": s.get("wall_time"),
                   "uptime_s": s.get("uptime_s")}
                  for h, s in zip(hosts, snaps)],
    }
    # schema provenance: the merged view carries the NEWEST schema seen;
    # hosts that disagree (a fleet mid-upgrade) are flagged per host under
    # ``schema_mismatch`` — the fold still runs (the sections are all
    # individually optional), but the disagreement is never silent, and the
    # loaders/CLIs surface it (seed-era snapshots read as version 0)
    schemas = {h: int(s.get("schema", 0) or 0)
               for h, s in zip(hosts, snaps)}
    out["schema"] = max(schemas.values())
    if len(set(schemas.values())) > 1:
        out["schema_mismatch"] = schemas
    # operators joined by name: counters summed, percentiles max'd
    ops: Dict[str, dict] = {}
    order: List[str] = []
    for host, s in zip(hosts, snaps):
        for row in s.get("operators") or []:
            if not isinstance(row, dict):
                continue                      # torn/partial host section
            name = row.get("name", "?")
            dst = ops.get(name)
            if dst is None:
                dst = ops[name] = {"name": name, "hosts": []}
                order.append(name)
            dst["hosts"].append(host)
            _sum_into(dst, {k: v for k, v in row.items()
                            if k not in ("service_time_us", "event_time",
                                         "counters", "watermark")})
            if row.get("counters"):
                dst.setdefault("counters", {})
                _sum_into(dst["counters"], row["counters"])
            if row.get("service_time_us"):
                st = dst.setdefault("service_time_us", {})
                samples = st.get("samples", 0) + \
                    row["service_time_us"].get("samples", 0)
                _max_into(st, row["service_time_us"])
                st["samples"] = samples
            if row.get("event_time"):
                dst.setdefault("event_time", {})
                _merge_et_section(dst["event_time"], row["event_time"])
    out["operators"] = [ops[n] for n in order]
    totals: dict = {}
    for s in snaps:
        _sum_into(totals, s.get("totals") or {})
    out["totals"] = totals
    queues: dict = {}
    for s in snaps:
        _max_into(queues, s.get("queues") or {})
    if queues:
        out["queues"] = queues
    recovery: dict = {}
    control_counters: dict = {}
    for s in snaps:
        _sum_into(recovery, s.get("recovery") or {})
        _sum_into(control_counters, (s.get("control") or {}).get("counters")
                  or {})
    out["recovery"] = recovery
    out["control"] = {"counters": control_counters}
    # e2e latency: worst-host percentiles + fleet sample count
    e2e: dict = {}
    for s in snaps:
        row = s.get("e2e_latency_us") or {}
        samples = e2e.get("samples", 0) + row.get("samples", 0)
        _max_into(e2e, row)
        e2e["samples"] = samples
    if e2e:
        out["e2e_latency_us"] = e2e
    # graph-level event time: the fleet frontier is the MIN across hosts
    ets = [(h, s.get("event_time")) for h, s in zip(hosts, snaps)
           if isinstance(s.get("event_time"), dict)]
    if ets:
        sec: dict = {}
        wm = [(e["min_watermark_ts"], h, e) for h, e in ets
              if "min_watermark_ts" in e]
        if wm:
            mn = min(wm, key=lambda t: t[0])
            sec["min_watermark_ts"] = mn[0]
            sec["frontier_host"] = mn[1]
            if mn[2].get("frontier_operator"):
                sec["frontier_operator"] = mn[2]["frontier_operator"]
        skews: dict = {}
        for _h, e in ets:
            _max_into(skews, e.get("edge_skew_ts") or {})
        if skews:
            sec["edge_skew_ts"] = skews
        out["event_time"] = sec
    # shard-local supervision: per-shard rows are folded HOST-TAGGED
    # (``host/shard``), never summed — a fleet view that summed shard
    # gauges could not name WHICH shard is hot, which is the whole point
    # of the per-shard health surface (names.py::SHARD_GAUGES)
    shard_secs = [(h, s.get("shards")) for h, s in zip(hosts, snaps)
                  if isinstance(s.get("shards"), dict)]
    if shard_secs:
        ssec: dict = {}
        for host, rows in shard_secs:
            for k, row in rows.items():
                ssec[f"{host}/{k}"] = dict(row)
        out["shards"] = ssec
    # SLO sections joined by SLO name: worst state wins (code MAX, the
    # host holding it named), burn rates MAX (the fleet view must show the
    # worst burn), pages summed AND host-tagged — an un-tagged page total
    # could not say WHICH host was paging.  The latest signal VALUE comes
    # from the worst (code, burn_fast) host, never a blanket MAX: for a
    # min-sense signal like hbm_headroom_pct, MAX would report the
    # HEALTHIEST host's headroom on a row whose state says another host
    # is paging
    slo_secs = [(h, s.get("slo")) for h, s in zip(hosts, snaps)
                if isinstance(s.get("slo"), dict)]
    if slo_secs:
        ssec: Dict[str, dict] = {}
        worst_key: Dict[str, tuple] = {}
        for host, rows in slo_secs:
            for name, row in rows.items():
                if not isinstance(row, dict):
                    continue                  # torn/partial host section
                dst = ssec.setdefault(name, {"state": "ok", "code": 0,
                                             "pages": 0,
                                             "pages_by_host": {}})
                code = int(row.get("code", 0))
                bf = row.get("burn_fast")
                key = (code, bf if isinstance(bf, (int, float)) else 0.0)
                if name not in worst_key or key > worst_key[name]:
                    worst_key[name] = key
                    dst["code"] = code
                    dst["state"] = row.get("state", dst["state"])
                    dst["worst_host"] = host
                    if row.get("signal") is not None:
                        dst["signal"] = row["signal"]
                    else:
                        dst.pop("signal", None)
                for k in ("burn_fast", "burn_slow"):
                    v = row.get(k)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        dst[k] = max(dst.get(k, v), v)
                if row.get("target") is not None and "target" not in dst:
                    dst["target"] = row["target"]
                pages = int(row.get("pages", 0))
                dst["pages"] += pages
                if pages:
                    dst["pages_by_host"][host] = pages
        out["slo"] = ssec
    # serving sections: run-level counters SUMMED (frames_torn across the
    # fleet is one total, like the telemetry fold), tenant rows joined by
    # tenant id and SUMMED per id (one tenant's fleet-wide shed pressure is
    # ONE series — the label is the tenant, not the host; the rate gauge
    # takes MIN, the tightest remediated bucket across hosts), graph labels
    # concatenated when hosts disagree mid-swap
    serv_secs = [(h, s.get("serving")) for h, s in zip(hosts, snaps)
                 if isinstance(s.get("serving"), dict)]
    if serv_secs:
        vsec: dict = {}
        tenants: Dict[str, dict] = {}
        graphs: List[str] = []
        for host, sec in serv_secs:
            g = sec.get("graph")
            if g and g not in graphs:
                graphs.append(g)
            _sum_into(vsec, {k: v for k, v in sec.items()
                             if isinstance(v, (int, float))
                             and not isinstance(v, bool)})
            for tid, row in (sec.get("tenants") or {}).items():
                if not isinstance(row, dict):
                    continue                  # torn/partial host section
                dst = tenants.setdefault(str(tid), {})
                rate = row.get("rate")
                # latency percentiles fold like SLO burn rates — MAX
                # across hosts (percentiles never sum), the exemplar
                # follows the worst host's p99; only the sample counters
                # ride the sum below
                pct_keys = ("e2e_p50_ms", "e2e_p95_ms", "e2e_p99_ms",
                            "e2e_p99_tick_ms")
                for k in pct_keys:
                    v = row.get(k)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        dst[k] = max(dst.get(k, v), v)
                p99 = row.get("e2e_p99_ms")
                if row.get("e2e_p99_exemplar") is not None \
                        and isinstance(p99, (int, float)) \
                        and p99 >= dst.get("e2e_p99_ms", p99):
                    dst["e2e_p99_exemplar"] = row["e2e_p99_exemplar"]
                _sum_into(dst, {k: v for k, v in row.items()
                                if k != "rate" and k not in pct_keys
                                and k != "e2e_p99_exemplar"})
                if isinstance(rate, (int, float)):
                    dst["rate"] = min(dst.get("rate", rate), rate)
        if graphs:
            vsec["graph"] = "+".join(graphs)
        if tenants:
            vsec["tenants"] = tenants
        out["serving"] = vsec
    # health ledgers: devices concatenated (host-tagged), footprints and
    # compile counters summed, device-time summed with the dispatch-bound
    # classifier recomputed over the fleet totals
    healths = [(h, s.get("health")) for h, s in zip(hosts, snaps)
               if isinstance(s.get("health"), dict)]
    if healths:
        hsec: dict = {"devices": []}
        state_bytes: dict = {}
        compile_tot: dict = {}
        dt: Dict[str, dict] = {}
        for host, hs in healths:
            for d in hs.get("devices", []):
                hsec["devices"].append(
                    dict(d, device=f"{host}/{d.get('device', '?')}"))
            _sum_into(state_bytes, hs.get("state_bytes") or {})
            _sum_into(compile_tot, hs.get("compile") or {})
            for label, row in (hs.get("device_time") or {}).items():
                acc = dt.setdefault(label, {"device_ms": 0.0,
                                            "dispatch_ms": 0.0, "samples": 0})
                _sum_into(acc, {k: row.get(k, 0) for k in
                                ("device_ms", "dispatch_ms", "samples")})
        if state_bytes:
            hsec["state_bytes"] = state_bytes
        if compile_tot:
            hsec["compile"] = compile_tot
        if dt:
            for row in dt.values():
                if row["device_ms"] > 0:
                    row["dispatch_ratio"] = round(
                        row["dispatch_ms"] / row["device_ms"], 4)
            hsec["device_time"] = dt
            bound = {lb: r["dispatch_ratio"] for lb, r in dt.items()
                     if r.get("dispatch_ratio", 0.0) >= DISPATCH_BOUND_RATIO}
            if bound:
                hsec["dispatch_bound"] = bound
        out["health"] = hsec
    return out


def merge_monitoring_dirs(paths: Sequence[str]):
    """(merged latest snapshot, merged index-aligned series, concatenated
    journal) over N per-host monitoring directories OR snapshots.jsonl
    files — the ``--merge`` entry point of wf_health.py / wf_state.py."""
    latests, serieses, journal, hosts = [], [], [], []
    for p in paths:
        mon_dir = os.path.dirname(p) if p.endswith(".jsonl") else p
        hosts.append(os.path.basename(os.path.normpath(mon_dir)) or mon_dir)
        latest, series = load_snapshots(mon_dir)
        latests.append(latest)
        serieses.append(series or [latest])
        journal.extend(load_journal(mon_dir))
    merged = merge_snapshots(latests, hosts=hosts)
    n_ticks = min(len(s) for s in serieses)
    merged_series = [merge_snapshots([s[i] for s in serieses], hosts=hosts)
                     for i in range(n_ticks)]
    journal.sort(key=lambda e: e.get("wall", 0.0))
    return merged, merged_series, journal
