"""Metrics primitives: log-bucket latency histograms + the graph-level registry.

The reference's ``MONITORING`` mode runs a per-second reporter that folds every
replica's ``Stats_Record`` into one graph-level JSON dump (SURVEY §5). This module
is that aggregation layer for the TPU port: :class:`MetricsRegistry` walks a live
``PipeGraph`` / ``Pipeline`` / ``CompiledChain``, sums replica counters, derives
live rates from successive snapshots, extracts watermark-lag gauges from TB window
states, and renders both a JSON snapshot and a Prometheus text exposition.

Latency distributions use :class:`LogHistogram` — fixed log-spaced buckets
(growth ``sqrt(2)``: every bucket's upper bound is ~41% above its lower bound, so
a reported percentile is within that factor of the true sample percentile).
Recording is O(log n_buckets) on the host (one ``bisect``), cheap enough to stay
always-on for the sampled service times (one sample per
``CompiledChain.SERVICE_SAMPLE_EVERY`` pushes).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .device_health import SNAPSHOT_SCHEMA as _SNAPSHOT_SCHEMA

#: histogram geometry: bounds[i] = BASE_S * GROWTH**i, spanning 1 us .. ~90 s
_BASE_S = 1e-6
_GROWTH = 2.0 ** 0.5
_N_BUCKETS = 54


class LogHistogram:
    """Log-spaced latency histogram (seconds). Thread-safe for concurrent
    ``record`` (reporter thread reads while driver threads write)."""

    #: shared, immutable upper bounds (seconds); the last bucket is +inf
    BOUNDS: List[float] = [_BASE_S * _GROWTH ** i for i in range(_N_BUCKETS)]

    def __init__(self):
        self.counts = [0] * (_N_BUCKETS + 1)      # +1 = overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        #: latency exemplars: bucket index -> the LAST trace id that landed
        #: there (observability/tracing.py) — links a percentile line in the
        #: snapshot to a concrete traced batch.  Populated only when callers
        #: pass ``exemplar=`` (tracing on), so the plain path pays one None
        #: check.
        self.exemplars: Dict[int, int] = {}
        self._lock = threading.Lock()

    def record(self, seconds: float, exemplar=None) -> None:
        s = float(seconds)
        if s < 0.0:
            s = 0.0
        i = bisect.bisect_left(self.BOUNDS, s)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += s
            if s < self.min:
                self.min = s
            if s > self.max:
                self.max = s
            if exemplar is not None:
                self.exemplars[i] = exemplar

    def _snap(self) -> tuple:
        """One consistent ``(counts, count, sum, min, max, exemplars)``
        read — the reporter thread summarizes while driver/stage threads
        record, so every read-side path (incl. the registry's cross-replica
        merge) works off a locked snapshot instead of walking the live
        fields (a torn counts/count pair would misplace a percentile, and
        iterating the live exemplars dict while record() inserts raises;
        surfaced by the WF260 concurrency lint)."""
        with self._lock:
            return (list(self.counts), self.count, self.sum, self.min,
                    self.max, dict(self.exemplars))

    @staticmethod
    def _bucket_of(counts: List[int], count: int, q: float) -> Optional[int]:
        """Index of the bucket holding the q-th sample; None when empty."""
        if not count:
            return None
        target = max(1, int(q / 100.0 * count + 0.5))
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                return i
        return len(counts) - 1

    @classmethod
    def _pct_value(cls, counts: List[int], count: int, mx: float,
                   q: float) -> float:
        """q-th percentile from one snapshot: the upper bound of the bucket
        holding the q-th sample (overflow bucket -> observed max) — an
        overestimate by at most one bucket width (factor sqrt(2)).  THE one
        bucket-to-value rule; percentile() and summary_us() both use it."""
        i = cls._bucket_of(counts, count, q)
        if i is None:
            return 0.0
        if i >= _N_BUCKETS:                      # overflow bucket
            return mx
        return min(cls.BOUNDS[i], mx)

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100])."""
        counts, count, _sum, _mn, mx, _ex = self._snap()
        return self._pct_value(counts, count, mx, q)

    def exemplar(self, q: float) -> Optional[int]:
        """Trace id of the last sample that landed in the q-th percentile's
        bucket (None when empty or never traced) — THE link from a histogram
        line to a concrete batch in the flight recorder."""
        counts, count, _sum, _mn, _mx, exemplars = self._snap()
        i = self._bucket_of(counts, count, q)
        return None if i is None else exemplars.get(i)

    @property
    def mean(self) -> float:
        _counts, count, total, _mn, _mx, _ex = self._snap()
        return total / count if count else 0.0

    def summary_us(self) -> Dict[str, float]:
        """p50/p95/p99 + mean in microseconds (the snapshot's unit), all
        computed from ONE consistent snapshot.  When tracing supplied
        exemplars, ``p99_exemplar`` names the trace id of the last batch
        that landed in the p99 bucket."""
        counts, count, total, _mn, mx, exemplars = self._snap()
        pct = lambda q: self._pct_value(counts, count, mx, q)
        out = {
            "p50": round(pct(50) * 1e6, 3),
            "p95": round(pct(95) * 1e6, 3),
            "p99": round(pct(99) * 1e6, 3),
            "mean": round((total / count if count else 0.0) * 1e6, 3),
            "max": round(mx * 1e6, 3) if count else 0.0,
            "samples": count,
        }
        i99 = self._bucket_of(counts, count, 99)
        ex = None if i99 is None else exemplars.get(i99)
        if ex is not None:
            out["p99_exemplar"] = ex
        return out

    def prometheus_buckets(self):
        """Cumulative (le_seconds, count) pairs, Prometheus histogram form."""
        counts, count, _sum, _mn, _mx, _ex = self._snap()
        out, acc = [], 0
        for i, c in enumerate(counts[:_N_BUCKETS]):
            acc += c
            out.append((self.BOUNDS[i], acc))
        out.append((float("inf"), count))
        return out


#: counter fields summed across replicas and exposed per operator
_COUNTERS = ("inputs_received", "outputs_sent", "bytes_received", "bytes_sent",
             "batches_received", "batches_sent", "num_kernels",
             "bytes_copied_hd", "bytes_copied_dh", "tuples_dropped_old")

#: HELP text per event-time gauge — checked against the central registry at
#: import so the exposition can never drift from names.py (the WF240/241
#: one-source-of-truth discipline)
_EVENT_TIME_HELP = {
    "watermark": "operator event-time frontier (max event ts seen)",
    "lag": "arrived-but-unfired event-time span",
    "occupancy_pct": "state-table occupancy percent",
    "pending_depth": "join-table upserts parked behind the watermark",
    "open_sessions": "open sessions in the session table",
    "oldest_open_age": "event-time age of the longest-open session",
    "archive_fill_pct": "interval-join archive fill percent (max of both "
                        "sides)",
    "lateness_p50": "observed lateness p50 (ticks; bucket upper bound)",
    "lateness_p99": "observed lateness p99 (ticks; bucket upper bound)",
    "min_watermark": "graph-level min-watermark frontier",
    "skew": "per-edge watermark skew (producer - consumer, ticks)",
}

#: snapshot section key -> registered event-time gauge name
_EVENT_TIME_KEY_MAP = {"watermark_ts": "watermark", "lag": "lag",
                       "occupancy_pct": "occupancy_pct",
                       "pending_depth": "pending_depth",
                       "open_sessions": "open_sessions",
                       "oldest_open_age": "oldest_open_age"}


def _check_event_time_names() -> None:
    from .names import EVENT_TIME_GAUGES
    if set(_EVENT_TIME_HELP) != set(EVENT_TIME_GAUGES):
        raise RuntimeError(
            f"metrics.py event-time exposition drifted from "
            f"names.py::EVENT_TIME_GAUGES: "
            f"{set(_EVENT_TIME_HELP) ^ set(EVENT_TIME_GAUGES)}")


_check_event_time_names()

#: HELP text per runtime-health gauge — checked against
#: ``names.py::HEALTH_GAUGES`` at import (the event-time lockstep
#: discipline): only registered names can render.  The ``hbm_*`` family
#: renders as ``windflow_hbm_<name>`` per device; the rest as
#: ``windflow_health_<name>``.
_HEALTH_HELP = {
    "hbm_headroom_bytes": "device memory limit minus bytes in use — the "
                          "tiered-state eviction signal",
    "hbm_bytes_in_use": "device memory bytes in use",
    "hbm_bytes_limit": "device memory limit (allocatable bytes)",
    "live_buffer_bytes": "process-wide live jax array bytes",
    "live_buffer_count": "process-wide live jax array count",
    "state_bytes": "operator state-pytree footprint (bytes)",
    "compiles": "chain program traces observed (compile ledger)",
    "retraces": "re-traces under a NEW shape/dtype signature "
                "(capacity switch, weak-type drift)",
    "retraces_unexpected": "re-traces of a warm executable under an "
                           "already-traced signature",
    "compile_seconds": "total seconds spent in journaled compiles",
    "device_ms": "sampled device execution time per stage (ms)",
    "dispatch_ms": "sampled host dispatch overhead per stage (ms)",
    "dispatch_ratio": "host dispatch / device time per stage — >= 0.5 "
                      "names a fusion candidate",
}


def _check_health_names() -> None:
    from .names import HEALTH_GAUGES
    if set(_HEALTH_HELP) != set(HEALTH_GAUGES):
        raise RuntimeError(
            f"metrics.py health exposition drifted from "
            f"names.py::HEALTH_GAUGES: "
            f"{set(_HEALTH_HELP) ^ set(HEALTH_GAUGES)}")


_check_health_names()

#: HELP text per SLO gauge — checked against ``names.py::SLO_GAUGES`` at
#: import (the event-time/health lockstep discipline).  Rendered as
#: ``windflow_slo_<name>{graph,slo="..."}`` from the snapshot's ``slo``
#: section (written by the SLO engine inside the Reporter tick).
_SLO_HELP = {
    "state": "SLO health state (0 ok, 1 warn, 2 page)",
    "burn_fast": "error-budget burn rate over the fast window",
    "burn_slow": "error-budget burn rate over the slow window",
    "signal": "latest observed value of the SLO's signal",
    "target": "the SLO's target threshold",
    "pages": "PAGE transitions this run",
}


def _check_slo_names() -> None:
    from .names import SLO_GAUGES
    if set(_SLO_HELP) != set(SLO_GAUGES):
        raise RuntimeError(
            f"metrics.py SLO exposition drifted from "
            f"names.py::SLO_GAUGES: {set(_SLO_HELP) ^ set(SLO_GAUGES)}")


_check_slo_names()

#: HELP text per telemetry-agent gauge — checked against
#: ``names.py::TELEMETRY_GAUGES`` at import (the SLO lockstep discipline).
#: Rendered as ``windflow_telemetry_<name>{graph}`` from the snapshot's
#: ``telemetry`` section (the TelemetryAgent stats the Reporter stamps in
#: when ``MonitoringConfig.telemetry`` is on — absent otherwise, so the
#: off path's artifacts are byte-identical).
_TELEMETRY_HELP = {
    "frames_sent": "telemetry frames delivered to the aggregator socket",
    "frames_dropped": "telemetry frames evicted by the bounded drop-oldest "
                      "outbox (a slow/dead aggregator costs frames, never "
                      "Reporter cadence)",
    "reconnects": "successful reconnects after a lost aggregator",
    "outbox_depth": "telemetry frames queued right now",
    "connected": "1 = live aggregator connection, 0 = not",
}


def _check_telemetry_names() -> None:
    from .names import TELEMETRY_GAUGES
    if set(_TELEMETRY_HELP) != set(TELEMETRY_GAUGES):
        raise RuntimeError(
            f"metrics.py telemetry exposition drifted from "
            f"names.py::TELEMETRY_GAUGES: "
            f"{set(_TELEMETRY_HELP) ^ set(TELEMETRY_GAUGES)}")


_check_telemetry_names()

#: HELP text per serving-plane gauge — checked against
#: ``names.py::SERVING_GAUGES`` at import (the telemetry lockstep
#: discipline).  Rendered as ``windflow_serving_<name>{graph}`` from the
#: snapshot's ``serving`` section (``ServingRuntime.serving_section`` via
#: ``attach_serving`` — absent when no serving runtime is attached, so the
#: off path's artifacts are byte-identical).
_SERVING_HELP = {
    "swaps_applied": "zero-downtime graph_swap cutovers completed",
    "swaps_rejected": "wire swap frames naming an unregistered graph",
    "frames_decoded": "intact WFS1 record frames ingested",
    "frames_torn": "ingest bytes resync'd past (torn client / garbage)",
    "frames_dup": "reconnect-overlap frames deduped by tenant seq",
    "clients_seen": "ingest connections accepted since serving start",
    "unknown_offered": "batches from tenant ids nobody declared",
}

#: HELP text per tenant gauge — checked against ``names.py::TENANT_GAUGES``
#: at import.  Rendered as ``windflow_tenant_<name>{graph,tenant="..."}``
#: from the ``serving.tenants`` rows (the per-label SHARD_GAUGES shape).
_TENANT_HELP = {
    "offered": "batches this tenant offered to its admission bucket",
    "admitted": "batches this tenant's controller admitted",
    "shed": "batches this tenant's controller shed",
    "shed_tuples": "tuple capacity this tenant's shed batches carried",
    "rate": "the tenant bucket's live refill rate",
    "e2e_p50_ms": "tenant e2e latency p50 (ms, cumulative)",
    "e2e_p95_ms": "tenant e2e latency p95 (ms, cumulative)",
    "e2e_p99_ms": "tenant e2e latency p99 (ms, cumulative)",
    "e2e_p99_tick_ms": "tenant e2e latency p99 over the last reporter tick "
                       "(ms; the tenant_e2e_p99_ms SLO signal)",
    "e2e_samples": "tenant e2e latency samples recorded",
    "e2e_samples_tick": "tenant e2e latency samples in the last tick",
    "e2e_p99_exemplar": "trace id of a batch observed in the tenant's p99 "
                        "latency bucket",
}


def _check_serving_names() -> None:
    from .names import SERVING_GAUGES, TENANT_GAUGES
    if set(_SERVING_HELP) != set(SERVING_GAUGES):
        raise RuntimeError(
            f"metrics.py serving exposition drifted from "
            f"names.py::SERVING_GAUGES: "
            f"{set(_SERVING_HELP) ^ set(SERVING_GAUGES)}")
    if set(_TENANT_HELP) != set(TENANT_GAUGES):
        raise RuntimeError(
            f"metrics.py tenant exposition drifted from "
            f"names.py::TENANT_GAUGES: "
            f"{set(_TENANT_HELP) ^ set(TENANT_GAUGES)}")


_check_serving_names()


def _recovery_counters() -> Dict[str, float]:
    """Process-wide supervision counters (lazy import: runtime.faults imports
    observability.journal, so the reverse edge must not exist at import time)."""
    from ..runtime import faults as _faults
    return _faults.counters()


def _control_section() -> Dict[str, Dict[str, float]]:
    """Process-wide control-plane counters (shed/throttle/switch totals) and
    gauges (chosen capacity) — lazy import for the same no-reverse-edge
    reason as the recovery counters."""
    from .. import control as _control
    return {"counters": _control.counters(), "gauges": _control.gauges()}


class MetricsRegistry:
    """Aggregates every ``Stats_Record`` of a running graph into one snapshot.

    Sources of truth are registered once and walked live at snapshot time (so
    lazily-compiled chains and late-built Ordering_Nodes are picked up):

    - ``register_graph(graph)``: a PipeGraph — walks ``_all_pipes()`` for
      sources, chains (ops + states), sinks, Ordering_Nodes, and (threaded
      driver) SPSC edge queues.
    - ``register_pipeline(pipeline)``: a linear Pipeline (source/chain/sink).
    - ``register_chain(label, chain)`` / ``register_operator(op)``: raw pieces
      (bench harnesses).

    ``snapshot()`` additionally derives per-operator input/output rates from
    the delta against the previous snapshot and pulls watermark-lag gauges out
    of TB window states (a tiny D2H read — monitoring-path only).
    """

    def __init__(self, name: str = "pipegraph", event_time: bool = False,
                 health_ledger=None, health: Optional[bool] = None):
        self.name = name
        #: runtime-health observability (MonitoringConfig.health): snapshots
        #: grow a graph-level ``health`` section — per-device memory gauges
        #: + headroom, per-operator state-pytree footprints, the compile/
        #: retrace ledger, sampled device-time attribution with the
        #: dispatch-bound classifier — and the Prometheus exposition the
        #: ``windflow_hbm_*``/``windflow_health_*`` gauges.  Host-side
        #: metadata reads only (shapes, memory_stats) — never a device sync.
        self._health_ledger = health_ledger
        self.health = bool(health_ledger is not None if health is None
                           else health)
        #: event-time observability (MonitoringConfig.event_time): snapshot
        #: rows grow per-operator ``event_time`` sections (watermarks, state
        #: occupancy, lateness histograms), the snapshot a graph-level
        #: ``event_time`` section (min-watermark frontier + per-edge skew),
        #: and the Prometheus exposition the ``windflow_event_time_*``
        #: gauges.  Snapshot-time D2H reads only — the monitoring path.
        self.event_time = bool(event_time)
        self.created = time.monotonic()
        self.e2e_hist = LogHistogram()       # source framing -> sink host receipt
        # registration happens on the driver while the graph is being built,
        # BEFORE the Monitor starts the reporter thread (happens-before via
        # Thread.start); the reporter tick only iterates — checked by the
        # WF260 concurrency lint, these annotations are its rationale
        self._graphs: List[Any] = []          # wf-lint: single-writer[driver]
        self._pipelines: List[Any] = []       # wf-lint: single-writer[driver]
        # (label, CompiledChain)
        self._chains: List[tuple] = []        # wf-lint: single-writer[driver]
        self._operators: List[Any] = []       # wf-lint: single-writer[driver]
        self._gauges: Dict[str, Callable[[], Any]] = {}  # wf-lint: single-writer[driver]
        self._queue_gauges: Dict[str, Callable[[], int]] = {}  # wf-lint: single-writer[driver]
        self._queue_capacities: Dict[str, int] = {}  # wf-lint: single-writer[driver]
        # id(op) -> (t, inputs, outputs)  # wf-lint: guarded-by[_lock]
        self._prev: Dict[int, tuple] = {}
        # written only inside snapshot(): reporter ticks are one thread, and
        # a driver-side snapshot (Reporter.stop final emit) runs only after
        # the tick thread is joined
        self._et_names: Dict[int, str] = {}   # wf-lint: single-writer[reporter]
        # previous tick's e2e bucket counts (same single-writer discipline):
        # the delta gives the PER-TICK p99 the SLO latency signal needs —
        # the cumulative histogram could never recover below a target once
        # a stall pushed its whole-run p99 over it
        self._e2e_prev_counts: Optional[List[int]] = None  # wf-lint: single-writer[reporter]
        # per-tenant e2e latency histograms (serving drive loop records,
        # reporter tick reads): the DICT itself is guarded — first sample
        # of a new tenant inserts while the reporter iterates — while each
        # histogram is internally locked like e2e_hist
        self._tenant_e2e: Dict[str, LogHistogram] = {}  # wf-lint: guarded-by[_lock]
        # previous tick's per-tenant bucket counts (reporter-only, the
        # _e2e_prev_counts windowed-p99 discipline per tenant)
        self._tenant_prev_counts: Dict[str, List[int]] = {}  # wf-lint: single-writer[reporter]
        self._lock = threading.Lock()

    # -- registration -----------------------------------------------------------------

    def register_graph(self, graph) -> None:
        self._graphs.append(graph)

    def register_pipeline(self, pipeline) -> None:
        self._pipelines.append(pipeline)

    def register_chain(self, label: str, chain) -> None:
        self._chains.append((label, chain))

    def register_operator(self, op) -> None:
        self._operators.append(op)

    def attach_gauge(self, name: str, fn: Callable[[], Any]) -> None:
        self._gauges[name] = fn

    def attach_shards(self, provider: Callable[[], dict]) -> None:
        """Register the sharded supervisor's per-shard health provider
        (``shard_report()``: {shard idx -> names.py::SHARD_GAUGES row}) —
        rendered as the snapshot's ``shards`` section and folded
        HOST-TAGGED (never summed) by ``device_health.merge_snapshots``,
        so the fleet view names WHICH shard is hot."""
        self._shards_provider = provider

    def attach_serving(self, provider: Callable[[], dict]) -> None:
        """Register a serving runtime's section provider
        (``ServingRuntime.serving_section``: graph/swap/frame counters +
        the per-tenant ``names.py::TENANT_GAUGES`` rows) — rendered as the
        snapshot's ``serving`` section and folded counters-summed,
        per-tenant-summed by ``device_health.merge_snapshots``."""
        self._serving_provider = provider

    def attach_queue_gauge(self, edge: str, fn: Callable[[], int],
                           capacity: Optional[int] = None) -> None:
        """SPSC ring depth probe for one dataflow edge (threaded driver):
        depth/capacity is the backpressure signal — a persistently full ring
        means the consumer pipe is the bottleneck. ``capacity`` (when known)
        is exposed alongside the depth, so watermark fractions are computable
        from the snapshot alone."""
        self._queue_gauges[edge] = fn
        if capacity is not None:
            self._queue_capacities[edge] = int(capacity)

    def record_e2e(self, seconds: float, exemplar=None) -> None:
        self.e2e_hist.record(seconds, exemplar=exemplar)

    def record_tenant_e2e(self, tenant: str, seconds: float,
                          exemplar=None) -> None:
        """One sampled wire-to-sink latency observation for ``tenant``
        (serving drive loop, same sampling cadence as ``record_e2e``) —
        feeds the per-tenant p50/p95/p99 rows of ``serving.tenants`` and
        the ``tenant_e2e_p99_ms`` SLO signal."""
        with self._lock:
            h = self._tenant_e2e.get(tenant)
            if h is None:
                h = self._tenant_e2e[tenant] = LogHistogram()
        h.record(seconds, exemplar=exemplar)

    def _tenant_latency_rows(self) -> Dict[str, dict]:
        """Per-tenant latency keys (names.py::TENANT_GAUGES e2e_* family)
        merged into the ``serving.tenants`` rows at snapshot time.  Reporter
        thread only (the _e2e_prev_counts discipline); tenants with zero
        samples yield nothing, so latency-off snapshots stay byte-identical."""
        with self._lock:
            hists = list(self._tenant_e2e.items())
        out: Dict[str, dict] = {}
        for tenant, h in hists:
            counts, count, _sum, _mn, mx, exemplars = h._snap()
            if not count:
                continue
            pct = lambda q: LogHistogram._pct_value(counts, count, mx, q)
            row = {
                "e2e_p50_ms": round(pct(50) * 1e3, 3),
                "e2e_p95_ms": round(pct(95) * 1e3, 3),
                "e2e_p99_ms": round(pct(99) * 1e3, 3),
                "e2e_samples": count,
            }
            i99 = LogHistogram._bucket_of(counts, count, 99)
            ex = None if i99 is None else exemplars.get(i99)
            if ex is not None:
                row["e2e_p99_exemplar"] = ex
            prev = self._tenant_prev_counts.get(tenant)
            if prev is not None:
                delta = [max(c - p, 0) for c, p in zip(counts, prev)]
                dn = sum(delta)
                row["e2e_samples_tick"] = dn
                row["e2e_p99_tick_ms"] = round(
                    LogHistogram._pct_value(delta, dn, mx, 99) * 1e3, 3)
            self._tenant_prev_counts[tenant] = counts
            out[tenant] = row
        return out

    # -- collection -------------------------------------------------------------------

    def _op_units(self):
        """Yield (op, state_or_None) for every operator currently visible."""
        seen = set()

        def emit(op, state=None):
            if op is None or id(op) in seen:
                return
            seen.add(id(op))
            yield op, state

        for g in self._graphs:
            for mp in g._all_pipes():
                if mp.source is not None:
                    yield from emit(mp.source)
                ch = mp._chain
                if ch is not None:
                    for op, st in zip(ch.ops, ch.states):
                        yield from emit(op, st)
                else:
                    for op in mp.ops:
                        yield from emit(op)
                if mp.sink is not None:
                    yield from emit(mp.sink)
        for p in self._pipelines:
            yield from emit(p.source)
            for op, st in zip(p.chain.ops, p.chain.states):
                yield from emit(op, st)
            if p.sink is not None:
                yield from emit(p.sink)
        for _, ch in self._chains:
            for op, st in zip(ch.ops, ch.states):
                yield from emit(op, st)
        for op in self._operators:
            yield from emit(op)

    @staticmethod
    def _watermark_gauge(op, state) -> Optional[dict]:
        """TB window frontier gauge from a window operator's carried state:
        ``wm`` (max event ts seen) vs the firing frontier ``next_win * slide``.
        ``lag`` is the span of arrived-but-unfired event time — the
        watermark-lag of the stage."""
        import numpy as np
        spec = getattr(op, "spec", None)
        if (spec is None or getattr(spec, "is_cb", True)
                or state is None
                or not hasattr(state, "wm") or not hasattr(state, "next_win")):
            return None
        import jax.errors
        try:
            wm = int(np.max(np.asarray(state.wm)))
            nxt = int(np.max(np.asarray(state.next_win)))
        except (RuntimeError, jax.errors.JAXTypeError):
            # the concrete failure modes of reading live window state
            # mid-run: a donated/deleted buffer materializes as RuntimeError
            # ("Array has been deleted"), an abstract value (snapshot racing
            # a trace) as TracerArrayConversionError/ConcretizationTypeError
            # (both JAXTypeError) — anything else is a bug that should
            # surface, not be swallowed
            return None
        frontier = nxt * spec.slide
        return {"watermark_ts": wm, "fire_frontier_ts": frontier,
                "lag_ts": max(wm - frontier + 1, 0) if wm >= 0 else 0}

    def snapshot(self) -> dict:
        """One graph-level snapshot: per-operator aggregated counters + rates +
        latency percentiles, watermark gauges, queue depths, e2e latency."""
        now = time.monotonic()
        ops_out = []
        et_secs: Dict[int, dict] = {}    # id(op) -> event_time section
        totals = {k: 0 for k in _COUNTERS}
        with self._lock:
            for op, state in self._op_units():
                # sync device-resident counters (e.g. Win_SeqFFAT.dropped_old)
                # into the host Stats_Record before reading it
                try:
                    op.collect_stats(state)
                except Exception:   # noqa: BLE001 — never kill a snapshot
                    pass
                recs = op.get_StatsRecords()
                row = {"name": op.getName(),
                       "replicas": len(recs),
                       "routing": op.getRoutingMode().name}
                for k in _COUNTERS:
                    v = sum(getattr(r, k, 0) for r in recs)
                    row[k] = v
                    totals[k] += v
                # service-time distribution: merged across replicas — each
                # replica read through its locked _snap() (stage threads
                # record concurrently; raw-field reads here were the torn-
                # count/mutating-dict race the WF260 lint surfaced)
                merged = LogHistogram()
                for r in recs:
                    h = getattr(r, "service_hist", None)
                    if h is None:
                        continue
                    counts, count, total, mn, mx, exemplars = h._snap()
                    if not count:
                        continue
                    for i, c in enumerate(counts):
                        merged.counts[i] += c
                    merged.count += count
                    merged.sum += total
                    merged.max = max(merged.max, mx)
                    merged.min = min(merged.min, mn)
                    merged.exemplars.update(exemplars)
                row["service_time_us"] = merged.summary_us()
                # rates vs the previous snapshot. Mid-chain operators count
                # batches/bytes, not tuples (per-tuple counts would need a
                # device sync per push), so batch + byte rates are the
                # universally-populated signals; tuple rates are live at the
                # host boundaries (sources count launches, sinks tuples).
                prev = self._prev.get(id(op))
                if prev is not None and now > prev[0]:
                    dt = now - prev[0]
                    row["rate_in_tps"] = round(
                        (row["inputs_received"] - prev[1]) / dt, 1)
                    row["rate_out_tps"] = round(
                        (row["outputs_sent"] - prev[2]) / dt, 1)
                    row["rate_batches_in_per_s"] = round(
                        (row["batches_received"] - prev[3]) / dt, 2)
                    row["rate_bytes_in_per_s"] = round(
                        (row["bytes_received"] - prev[4]) / dt, 1)
                else:
                    up = max(now - self.created, 1e-9)
                    row["rate_in_tps"] = round(row["inputs_received"] / up, 1)
                    row["rate_out_tps"] = round(row["outputs_sent"] / up, 1)
                    row["rate_batches_in_per_s"] = round(
                        row["batches_received"] / up, 2)
                    row["rate_bytes_in_per_s"] = round(
                        row["bytes_received"] / up, 1)
                self._prev[id(op)] = (now, row["inputs_received"],
                                      row["outputs_sent"],
                                      row["batches_received"],
                                      row["bytes_received"])
                wmg = self._watermark_gauge(op, state)
                if wmg is not None:
                    row["watermark"] = wmg
                # per-stage counters published by collect_stats (PR 8
                # operator counters on a uniform per-operator surface)
                sc = op.stage_counters() if hasattr(op, "stage_counters") \
                    else {}
                if sc:
                    row["counters"] = sc
                if self.event_time:
                    import jax.errors
                    try:
                        sec = op.event_time_stats(state)
                    except (RuntimeError, jax.errors.JAXTypeError):
                        # same live-state read hazards as _watermark_gauge:
                        # donated buffer / abstract value mid-trace
                        sec = None
                    if sec is not None:
                        row["event_time"] = sec
                        et_secs[id(op)] = sec
                        self._et_names[id(op)] = op.getName()
                    elif wmg is not None:
                        # TB window ops without a richer section still carry
                        # a frontier — include them in the watermark map
                        et_secs[id(op)] = {"watermark_ts":
                                           wmg["watermark_ts"]}
                        self._et_names[id(op)] = op.getName()
                ops_out.append(row)
        queues = {}
        for edge, fn in list(self._queue_gauges.items()):
            try:
                queues[edge] = int(fn())
            except Exception:       # noqa: BLE001 — queue freed after EOS
                queues[edge] = 0
        gauges = {}
        for gname, fn in list(self._gauges.items()):
            try:
                gauges[gname] = fn()
            except Exception:       # noqa: BLE001
                pass
        orderings = []
        for g in self._graphs:
            for i, mp in enumerate(g._all_pipes()):
                o = mp._ordering
                if o is not None:
                    orderings.append({
                        "pipe": i,
                        "pending_capacity": (0 if o._pending is None
                                             else int(o._pending.capacity)),
                        # the RAW settled value (o._last_release_count), not
                        # the settling property: the reporter thread must
                        # neither force a device sync on the driver's async
                        # counts readback nor race its deferred pool trim —
                        # settle() is restricted to the node's owning
                        # thread by its `wf-lint: thread-role[driver,
                        # stage]` annotation (parallel/ordering.py; WF261
                        # fails the gate if the reporter ever reaches it) —
                        # telemetry may lag the in-flight push by one
                        "last_release_count": int(o._last_release_count),
                        "mode": o.mode.name,
                    })
        e2e = self.e2e_hist.summary_us()
        # per-tick e2e latency: percentile over ONLY the samples recorded
        # since the previous snapshot (bucket-count delta) — the windowed
        # signal the SLO engine's "e2e_p99_ms" reads, so a recovered stream
        # can flip PAGE back to OK while the cumulative p50/p95/p99 above
        # still carry the incident
        counts, _cnt, _sum, _mn, mx, _ex = self.e2e_hist._snap()
        if self._e2e_prev_counts is not None:
            delta = [max(c - p, 0) for c, p in
                     zip(counts, self._e2e_prev_counts)]
            dn = sum(delta)
            e2e["samples_tick"] = dn
            e2e["p99_tick"] = round(
                LogHistogram._pct_value(delta, dn, mx, 99) * 1e6, 3)
        self._e2e_prev_counts = counts
        snap = {
            "graph": self.name,
            # snapshot schema version (device_health.SNAPSHOT_SCHEMA):
            # merge_snapshots refuses to SILENTLY fold hosts that disagree
            # (a heterogeneous fleet mid-upgrade must be detectable)
            "schema": _SNAPSHOT_SCHEMA,
            "wall_time": time.time(),
            "uptime_s": round(now - self.created, 3),
            "operators": ops_out,
            "totals": totals,
            "e2e_latency_us": e2e,
            "queues": queues,
            "ordering": orderings,
            # process-wide recovery/chaos counters (restarts, backoff sleeps,
            # dead-lettered poison batches, checkpoint validation outcomes,
            # watchdog timeouts, injected faults) — runtime/faults.py
            "recovery": _recovery_counters(),
            # control-plane counters/gauges (shed/throttle/capacity-switch
            # totals, chosen capacity) — windflow_tpu/control
            "control": _control_section(),
        }
        if self._queue_capacities:
            snap["queue_capacity"] = dict(self._queue_capacities)
        if gauges:
            snap["gauges"] = gauges
        shards_fn = getattr(self, "_shards_provider", None)
        if shards_fn is not None:
            try:
                rows = shards_fn()
            except Exception:       # noqa: BLE001 — never kill a snapshot
                rows = None
            if rows:
                # string keys: the section round-trips through JSON
                snap["shards"] = {str(k): dict(v) for k, v in rows.items()}
        serving_fn = getattr(self, "_serving_provider", None)
        if serving_fn is not None:
            try:
                sec = serving_fn()
            except Exception:       # noqa: BLE001 — never kill a snapshot
                sec = None
            if sec:
                # join per-tenant latency into the tenant rows (tenants the
                # registry declared but latency never sampled keep their
                # exact PR 18 shape — the off path stays byte-identical)
                lat = self._tenant_latency_rows()
                if lat:
                    tenants = sec.setdefault("tenants", {})
                    for tid, extra in lat.items():
                        tenants.setdefault(tid, {}).update(extra)
                snap["serving"] = sec
        if self.event_time:
            et = self._event_time_section(et_secs)
            if et:
                snap["event_time"] = et
        if self.health:
            snap["health"] = self._health_section()
        return snap

    def _iter_health_chains(self):
        """Every live CompiledChain visible to this registry (deduped) —
        the state-footprint walk of the health section."""
        seen = set()
        chains = []
        for g in self._graphs:
            for mp in g._all_pipes():
                chains.append(mp._chain)
        for p in self._pipelines:
            chains.append(getattr(p, "chain", None))
        for _, ch in self._chains:
            chains.append(ch)
        for ch in chains:
            if ch is not None and id(ch) not in seen:
                seen.add(id(ch))
                yield ch

    def _health_section(self) -> dict:
        """The runtime-health ledger, snapshot-shaped: HBM devices +
        headroom, live-buffer totals, per-operator state footprints (static
        shape metadata — no device sync), and — when a ledger is active —
        the compile/retrace counters, executable footprints, and the
        sampled device-time attribution with its dispatch-bound
        classifier."""
        from . import device_health as _dh
        sec: dict = {"devices": _dh.device_memory()}
        sec.update(_dh.live_buffer_stats())
        state_bytes: Dict[str, int] = {}
        for ch in self._iter_health_chains():
            try:
                fp = ch.state_footprints()
            except Exception:   # noqa: BLE001 — never kill a snapshot
                continue
            for op_name, nbytes in fp.items():
                state_bytes[op_name] = state_bytes.get(op_name, 0) + nbytes
        if state_bytes:
            sec["state_bytes"] = state_bytes
        led = self._health_ledger or _dh.get_active()
        if led is not None:
            sec.update(led.snapshot_section())
        risky = _dh.headroom_risks(sec["devices"])
        if risky:
            sec["headroom_risk"] = risky
        return sec

    def _event_time_section(self, et_secs: Dict[int, dict]) -> dict:
        """Graph-level watermark propagation map: the min-watermark frontier
        (the operator holding the whole graph's event time back) and the
        per-edge watermark *skew* — producer-pipe watermark minus consumer-
        pipe watermark over the SAME ``_iter_edges`` enumeration the
        threaded driver builds its rings from (edge labels match queue
        gauges and the topology export, which annotates its edges from this
        section)."""
        out: dict = {}
        wms = []
        for g in self._graphs:
            for mp in g._all_pipes():
                for op in mp.ops:
                    sec = et_secs.get(id(op))
                    if sec and "watermark_ts" in sec:
                        wms.append((sec["watermark_ts"], op.getName()))
        if not wms:
            # linear pipelines / raw chains: no pipe structure — frontier
            # from every section (the loop stored the owning op's name)
            for oid, sec in et_secs.items():
                if "watermark_ts" in sec:
                    wms.append((sec["watermark_ts"],
                                self._et_names.get(oid)))
        if wms:
            mn = min(wms, key=lambda t: t[0])
            out["min_watermark_ts"] = mn[0]
            if mn[1]:
                out["frontier_operator"] = mn[1]
        edges = {}
        for g in self._graphs:
            wm_of_pipe = {}
            for mp in g._all_pipes():
                pw = [et_secs[id(op)]["watermark_ts"] for op in mp.ops
                      if id(op) in et_secs
                      and "watermark_ts" in et_secs[id(op)]]
                if pw:
                    wm_of_pipe[id(mp)] = max(pw)
            try:
                edge_iter = list(g._iter_edges())
            except Exception:       # noqa: BLE001 — half-built graph
                continue
            for prod, cons, label, _idx in edge_iter:
                if prod is None:
                    continue
                a = wm_of_pipe.get(id(prod))
                b = wm_of_pipe.get(id(cons))
                if a is not None and b is not None:
                    edges[label] = a - b
        if edges:
            out["edge_skew_ts"] = edges
        return out

    # -- Prometheus text exposition ----------------------------------------------------

    @staticmethod
    def _prometheus_health(snap: dict, lines: List[str], esc) -> None:
        """``windflow_hbm_*`` (per device) + ``windflow_health_*`` gauges
        from the snapshot's health section.  Only the names registered in
        ``names.py::HEALTH_GAUGES`` render (the import-time lockstep check
        above); absent values (e.g. ``memory_stats`` on a CPU backend)
        simply do not render."""
        sec = snap.get("health")
        if not sec:
            return
        g = snap["graph"]
        typed = set()

        def head(metric, name):
            if metric not in typed:
                typed.add(metric)
                lines.append(f"# HELP {metric} {_HEALTH_HELP[name]}")
                lines.append(f"# TYPE {metric} gauge")

        for d in sec.get("devices", []):
            lab = f'graph="{esc(g)}",device="{esc(d.get("device", "?"))}"'
            for name in ("hbm_bytes_in_use", "hbm_bytes_limit",
                         "hbm_headroom_bytes"):
                v = d.get(name[4:])      # row keys drop the hbm_ prefix
                if v is not None:
                    head(f"windflow_{name}", name)
                    lines.append(f'windflow_{name}{{{lab}}} {v}')
        glab = f'graph="{esc(g)}"'
        for name in ("live_buffer_bytes", "live_buffer_count"):
            if sec.get(name) is not None:
                head(f"windflow_health_{name}", name)
                lines.append(f'windflow_health_{name}{{{glab}}} {sec[name]}')
        for op_name, nbytes in sorted((sec.get("state_bytes") or {}).items()):
            head("windflow_health_state_bytes", "state_bytes")
            lines.append(f'windflow_health_state_bytes{{{glab},'
                         f'operator="{esc(op_name)}"}} {nbytes}')
        comp = sec.get("compile") or {}
        for name, key in (("compiles", "compiles"), ("retraces", "retraces"),
                          ("retraces_unexpected", "retraces_unexpected"),
                          ("compile_seconds", "compile_s_total")):
            if comp.get(key) is not None:
                head(f"windflow_health_{name}", name)
                lines.append(f'windflow_health_{name}{{{glab}}} {comp[key]}')
        for label, row in sorted((sec.get("device_time") or {}).items()):
            slab = f'{glab},stage="{esc(label)}"'
            for name, key in (("device_ms", "device_ms"),
                              ("dispatch_ms", "dispatch_ms"),
                              ("dispatch_ratio", "dispatch_ratio")):
                if row.get(key) is not None:
                    head(f"windflow_health_{name}", name)
                    lines.append(f'windflow_health_{name}{{{slab}}} '
                                 f'{row[key]}')

    @staticmethod
    def _prometheus_slo(snap: dict, lines: List[str], esc) -> None:
        """``windflow_slo_*`` gauges from the snapshot's ``slo`` section
        (one label set per SLO).  Only the names registered in
        ``names.py::SLO_GAUGES`` render (the import-time lockstep check
        above); ``state`` renders its numeric code."""
        sec = snap.get("slo")
        if not sec:
            return
        g = snap["graph"]
        typed = set()

        def head(name):
            if name not in typed:
                typed.add(name)
                lines.append(f"# HELP windflow_slo_{name} {_SLO_HELP[name]}")
                lines.append(f"# TYPE windflow_slo_{name} gauge")

        for slo_name, row in sorted(sec.items()):
            lab = f'graph="{esc(g)}",slo="{esc(slo_name)}"'
            for name in ("burn_fast", "burn_slow", "signal", "target",
                         "pages"):
                v = row.get(name)
                if v is not None:
                    head(name)
                    lines.append(f'windflow_slo_{name}{{{lab}}} {v}')
            if row.get("code") is not None:
                head("state")
                lines.append(f'windflow_slo_state{{{lab}}} {row["code"]}')

    @staticmethod
    def _prometheus_telemetry(snap: dict, lines: List[str], esc) -> None:
        """``windflow_telemetry_*`` gauges from the snapshot's ``telemetry``
        section (the TelemetryAgent stats — present only when the fleet
        telemetry plane is on).  Only the names registered in
        ``names.py::TELEMETRY_GAUGES`` render (the import-time lockstep
        check above)."""
        sec = snap.get("telemetry")
        if not sec:
            return
        g = snap["graph"]
        for name in sorted(_TELEMETRY_HELP):
            v = sec.get(name)
            if v is None:
                continue
            lines.append(f"# HELP windflow_telemetry_{name} "
                         f"{_TELEMETRY_HELP[name]}")
            lines.append(f"# TYPE windflow_telemetry_{name} gauge")
            lines.append(f'windflow_telemetry_{name}{{graph="{esc(g)}"}} '
                         f'{v}')

    @staticmethod
    def _prometheus_serving(snap: dict, lines: List[str], esc) -> None:
        """``windflow_serving_*`` run-level gauges + ``windflow_tenant_*``
        per-tenant gauges from the snapshot's ``serving`` section.  Only
        names registered in ``names.py::SERVING_GAUGES``/``TENANT_GAUGES``
        render (the import-time lockstep check above)."""
        sec = snap.get("serving")
        if not sec:
            return
        g = snap["graph"]
        for name in sorted(_SERVING_HELP):
            v = sec.get(name)
            if v is None:
                continue
            lines.append(f"# HELP windflow_serving_{name} "
                         f"{_SERVING_HELP[name]}")
            lines.append(f"# TYPE windflow_serving_{name} gauge")
            lines.append(f'windflow_serving_{name}{{graph="{esc(g)}"}} {v}')
        tenants = sec.get("tenants") or {}
        typed = set()

        def head(name):
            if name not in typed:
                typed.add(name)
                lines.append(f"# HELP windflow_tenant_{name} "
                             f"{_TENANT_HELP[name]}")
                lines.append(f"# TYPE windflow_tenant_{name} gauge")

        for tid, row in sorted(tenants.items()):
            lab = f'graph="{esc(g)}",tenant="{esc(tid)}"'
            for name in sorted(_TENANT_HELP):
                v = row.get(name)
                if v is not None:
                    head(name)
                    lines.append(f'windflow_tenant_{name}{{{lab}}} {v}')

    @staticmethod
    def _prometheus_event_time(snap: dict, lines: List[str], esc) -> None:
        """``windflow_event_time_*`` gauges (HELP/TYPE'd) from the snapshot's
        event-time sections: per-operator watermark/lag/occupancy/pressure,
        per-(operator, stream) lateness quantiles, and the graph-level
        min-watermark frontier + per-edge skew.  Only the names registered
        in ``names.py::EVENT_TIME_GAUGES`` render (the module-level check
        below keeps the local maps and the registry in lockstep)."""
        g = snap["graph"]
        help_of = _EVENT_TIME_HELP
        key_map = _EVENT_TIME_KEY_MAP
        typed = set()

        def head(name):
            if name not in typed:
                typed.add(name)
                lines.append(f"# HELP windflow_event_time_{name} "
                             f"{help_of[name]}")
                lines.append(f"# TYPE windflow_event_time_{name} gauge")

        for row in snap["operators"]:
            sec = row.get("event_time")
            if not sec:
                continue
            lab = f'graph="{esc(g)}",operator="{esc(row["name"])}"'
            for key, gname in key_map.items():
                if key in sec:
                    head(gname)
                    lines.append(
                        f'windflow_event_time_{gname}{{{lab}}} {sec[key]}')
            fills = [v for k, v in sec.items() if k.endswith("_fill_pct")]
            if fills:
                head("archive_fill_pct")
                lines.append(f'windflow_event_time_archive_fill_pct{{{lab}}} '
                             f'{max(fills)}')
            for stream, summ in (sec.get("lateness") or {}).items():
                if not summ.get("total"):
                    continue
                slab = f'{lab},stream="{esc(stream)}"'
                for q in ("p50", "p99"):
                    head(f"lateness_{q}")
                    lines.append(f'windflow_event_time_lateness_{q}'
                                 f'{{{slab}}} {summ[q]}')
        et = snap.get("event_time") or {}
        if "min_watermark_ts" in et:
            head("min_watermark")
            lines.append(f'windflow_event_time_min_watermark'
                         f'{{graph="{esc(g)}"}} {et["min_watermark_ts"]}')
        for edge, skew in sorted((et.get("edge_skew_ts") or {}).items()):
            head("skew")
            lines.append(f'windflow_event_time_skew{{graph="{esc(g)}",'
                         f'edge="{esc(edge)}"}} {skew}')

    def to_prometheus(self, snap: Optional[dict] = None) -> str:
        """Render the snapshot in the Prometheus text format (one scrape body).
        Metric names: ``windflow_<counter>_total`` per-operator counters,
        ``windflow_service_time_seconds`` / ``windflow_e2e_latency_seconds``
        histograms, ``windflow_queue_depth`` / ``windflow_watermark_lag``
        gauges."""
        snap = snap or self.snapshot()
        g = snap["graph"]
        lines = []

        def esc(s):
            return str(s).replace("\\", "\\\\").replace('"', '\\"')

        for c in _COUNTERS:
            lines.append(f"# TYPE windflow_{c}_total counter")
            for row in snap["operators"]:
                lines.append(
                    f'windflow_{c}_total{{graph="{esc(g)}",'
                    f'operator="{esc(row["name"])}"}} {row[c]}')
        lines.append("# TYPE windflow_rate_in_tps gauge")
        for row in snap["operators"]:
            lines.append(f'windflow_rate_in_tps{{graph="{esc(g)}",'
                         f'operator="{esc(row["name"])}"}} {row["rate_in_tps"]}')
        lines.append("# TYPE windflow_watermark_lag gauge")
        for row in snap["operators"]:
            if "watermark" in row:
                lines.append(
                    f'windflow_watermark_lag{{graph="{esc(g)}",'
                    f'operator="{esc(row["name"])}"}} '
                    f'{row["watermark"]["lag_ts"]}')
        # per-stage operator counters/gauges (names.py::STAGE_COUNTERS /
        # STAGE_GAUGES — only registered names render, the WF240/241
        # discipline), with HELP lines: these are the PR 8 operator counters
        # promoted to a uniform per-operator exposition
        from .names import STAGE_COUNTERS, STAGE_GAUGES
        stage_help = {
            "sessions_closed": "sessions closed by the session triggerer",
            "topn_evictions": "leaderboard candidates evicted by the top-N "
                              "rank merge",
            "match_drops": "interval-join matches dropped past max_matches",
            "arch_drops": "live interval-join archive slots overwritten",
            "overflow_drops": "join-table pending-ring/table overflow drops",
            "old_drops": "tuples dropped as OLD behind the event-time "
                         "frontier",
            "join_table_version": "applied upsert count of the operator's "
                                  "join table",
        }
        for c in STAGE_COUNTERS + STAGE_GAUGES:
            rows = [r for r in snap["operators"]
                    if c in (r.get("counters") or {})]
            if not rows:
                continue
            kind = "gauge" if c in STAGE_GAUGES else "counter"
            suffix = "" if kind == "gauge" else "_total"
            lines.append(f"# HELP windflow_stage_{c}{suffix} "
                         f"{stage_help.get(c, c)}")
            lines.append(f"# TYPE windflow_stage_{c}{suffix} {kind}")
            for row in rows:
                lines.append(
                    f'windflow_stage_{c}{suffix}{{graph="{esc(g)}",'
                    f'operator="{esc(row["name"])}"}} {row["counters"][c]}')
        self._prometheus_event_time(snap, lines, esc)
        self._prometheus_health(snap, lines, esc)
        self._prometheus_slo(snap, lines, esc)
        self._prometheus_telemetry(snap, lines, esc)
        self._prometheus_serving(snap, lines, esc)
        lines.append("# TYPE windflow_queue_depth gauge")
        for edge, depth in snap["queues"].items():
            lines.append(f'windflow_queue_depth{{graph="{esc(g)}",'
                         f'edge="{esc(edge)}"}} {depth}')
        qcaps = snap.get("queue_capacity") or {}
        if qcaps:
            lines.append("# TYPE windflow_queue_capacity gauge")
            for edge, cap in qcaps.items():
                lines.append(f'windflow_queue_capacity{{graph="{esc(g)}",'
                             f'edge="{esc(edge)}"}} {cap}')
        # service-time histograms, straight from the live LogHistograms
        lines.append("# TYPE windflow_service_time_seconds histogram")
        with self._lock:
            for op, _state in self._op_units():
                for r in op.get_StatsRecords():
                    h = getattr(r, "service_hist", None)
                    if h is None or not h.count:
                        continue
                    lab = (f'graph="{esc(g)}",operator="{esc(op.getName())}",'
                           f'replica="{r.replica_id}"')
                    for le, acc in h.prometheus_buckets():
                        le_s = "+Inf" if le == float("inf") else f"{le:.9g}"
                        lines.append(
                            f'windflow_service_time_seconds_bucket'
                            f'{{{lab},le="{le_s}"}} {acc}')
                    lines.append(
                        f'windflow_service_time_seconds_sum{{{lab}}} {h.sum:.9g}')
                    lines.append(
                        f'windflow_service_time_seconds_count{{{lab}}} {h.count}')
        h = self.e2e_hist
        if h.count:
            lines.append("# TYPE windflow_e2e_latency_seconds histogram")
            lab = f'graph="{esc(g)}"'
            for le, acc in h.prometheus_buckets():
                le_s = "+Inf" if le == float("inf") else f"{le:.9g}"
                lines.append(f'windflow_e2e_latency_seconds_bucket'
                             f'{{{lab},le="{le_s}"}} {acc}')
            lines.append(f'windflow_e2e_latency_seconds_sum{{{lab}}} {h.sum:.9g}')
            lines.append(f'windflow_e2e_latency_seconds_count{{{lab}}} {h.count}')
        recovery = snap.get("recovery") or _recovery_counters()
        for k, v in sorted(recovery.items()):
            lines.append(f"# TYPE windflow_recovery_{k}_total counter")
            lines.append(f'windflow_recovery_{k}_total{{graph="{esc(g)}"}} '
                         f'{round(v, 6)}')
        control = snap.get("control") or _control_section()
        for k, v in sorted((control.get("counters") or {}).items()):
            lines.append(f"# TYPE windflow_control_{k}_total counter")
            lines.append(f'windflow_control_{k}_total{{graph="{esc(g)}"}} '
                         f'{round(v, 6)}')
        for k, v in sorted((control.get("gauges") or {}).items()):
            lines.append(f"# TYPE windflow_control_{k} gauge")
            lines.append(f'windflow_control_{k}{{graph="{esc(g)}"}} {v}')
        lines.append(f'windflow_uptime_seconds{{graph="{esc(g)}"}} '
                     f'{snap["uptime_s"]}')
        return "\n".join(lines) + "\n"
