"""Unified telemetry for windflow_tpu — the reference's MONITORING mode, grown up.

Upstream WindFlow's ``MONITORING`` build aggregates every replica's
``Stats_Record`` into a per-second graph-level JSON dump plus a graphviz
diagram of the PipeGraph (SURVEY §5). This package is that layer for the TPU
port, wired through the runtime:

- :class:`MetricsRegistry` (``metrics.py``): graph-level aggregation of all
  ``Stats_Record``s + log-bucket latency histograms (p50/p95/p99 batch service
  time, end-to-end source→sink latency), watermark-lag gauges for TB windows,
  SPSC queue-depth gauges under the threaded driver.
- :class:`Reporter` (``reporter.py``): periodic daemon thread emitting JSON
  snapshots and Prometheus text exposition to files.
- :class:`EventJournal` (``journal.py``): JSONL spans for checkpoint/restore/
  restart, ordering-buffer flushes, EOS propagation, sampled program launches.
- ``topology.py``: dot + JSON export of the compiled graph, annotated with
  live per-edge rates and queue depths.

Everything is **off by default** (zero hot-path cost beyond a None check) and
enabled per graph/pipeline via ``PipeGraph(..., monitoring=...)`` /
``Pipeline(..., monitoring=...)`` or process-wide via ``WF_MONITORING``:

    WF_MONITORING=1              # defaults: ./wf_monitoring, 1 s interval
    WF_MONITORING=/path/out      # same, custom output directory
    WF_MONITORING_INTERVAL=0.25  # reporter interval override (seconds)
    WF_MONITORING_EVENT_TIME=1   # event-time sub-toggle (watermark map +
                                 # on-device lateness histograms; see
                                 # MonitoringConfig.event_time)
    WF_SLO=1                     # SLO-engine sub-toggle (burn-rate alerting
                                 # + incident bundles; '1' = default specs,
                                 # else JSON path/inline; see
                                 # MonitoringConfig.slo + slo.py)
    WF_SNAPSHOT_KEEP=500         # snapshots.jsonl keep-last-N retention
    WF_TELEMETRY=tcp://agg:9901  # fleet telemetry sub-toggle ('1' = endpoint
                                 # from WF_TELEMETRY_ENDPOINT, else the value
                                 # IS the endpoint; see
                                 # MonitoringConfig.telemetry + fleet.py)
    WF_REMEDIATION=1             # self-driving remediation sub-toggle ('1' =
                                 # default policy, else JSON path/inline;
                                 # requires the SLO engine; see
                                 # MonitoringConfig.remediation +
                                 # control/remediation.py)
    WF_PROFILE=1                 # profile-on-page sub-toggle: bounded
                                 # jax.profiler capture committed into
                                 # incident bundles (requires the SLO
                                 # engine; see MonitoringConfig.profile +
                                 # profiling.py)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Union

from .journal import EventJournal, read_journal, set_active as set_journal
from .metrics import LogHistogram, MetricsRegistry
from . import device_health
from . import event_time
from . import slo as slo_engine
from .names import (CONTROL_COUNTERS, CONTROL_GAUGES, JOURNAL_EVENTS,
                    RECOVERY_COUNTERS, TRACE_RECORD_KINDS, TRACE_STAGES)
from .reporter import Reporter
from .topology import (graph_topology_dot, graph_topology_json,
                       pipeline_topology_dot, pipeline_topology_json,
                       topology_dot, topology_json)
from .tracing import TraceConfig, Tracer
from . import journal, profiling, tracing

__all__ = [
    "LogHistogram", "MetricsRegistry", "Reporter", "EventJournal",
    "MonitoringConfig", "Monitor", "journal", "read_journal", "set_journal",
    "TraceConfig", "Tracer", "tracing", "event_time", "event_time_enabled",
    "device_health", "slo_engine", "profiling",
    "topology_dot", "topology_json", "graph_topology_dot",
    "graph_topology_json", "pipeline_topology_dot", "pipeline_topology_json",
]


@dataclasses.dataclass
class MonitoringConfig:
    """Resolved monitoring settings for one graph/pipeline run."""

    out_dir: str = "wf_monitoring"
    interval_s: float = 1.0
    prometheus: bool = True
    journal: bool = True
    #: None = flush the event journal per event (crash-safe, the supervised
    #: default); an int N = batched mode, flushed every N events (and always
    #: on errors/close) — for tracing-heavy runs where a syscall per sampled
    #: span would dominate (see EventJournal)
    journal_flush_interval: "Optional[int]" = None
    #: sample every Nth source batch for the end-to-end latency histogram
    #: (a sample is two perf_counter reads around a sink receipt that is
    #: host-synchronous anyway — cheap, so the default is dense)
    e2e_sample_every: int = 4
    #: event-time observability sub-toggle (off by default): per-operator
    #: ``event_time`` snapshot sections (watermarks, state occupancy,
    #: pending/archive pressure), the graph-level min-watermark frontier +
    #: per-edge skew gauges, and on-device lateness histograms folded into
    #: every stateful operator's state (``observability/event_time.py``).
    #: GEOMETRY-BINDING: the histograms live in the operator state pytrees,
    #: so this toggle is resolved when a chain is BUILT (the ``control=``
    #: convention, not the lazy monitoring resolution) — off means the
    #: compiled programs are byte-for-byte today's (zero added device work,
    #: the perf-gate pins unchanged); on changes only the carried state,
    #: never the results (chaos-pinned byte-identical).  Env override:
    #: ``WF_MONITORING_EVENT_TIME`` (``''``/``'0'`` off, anything else on).
    event_time: bool = False
    #: runtime-health sub-toggle (off by default): the HBM memory ledger
    #: (per-device ``memory_stats``/live-buffer gauges, per-operator state
    #: footprints, executable footprints, ``windflow_hbm_headroom_bytes``),
    #: the compile/retrace ledger (every chain-program trace journaled with
    #: cause/cache-key/duration/AOT cost + the unexpected-retrace
    #: detector), and sampled device-time attribution with the per-stage
    #: dispatch-bound classifier (``observability/device_health.py``).
    #: Purely host-side — unlike ``event_time`` this is NOT geometry-
    #: binding: compiled programs, operator state, and the perf-gate cost
    #: pins are byte-for-byte unchanged either way (the ledger hooks in
    #: the jitted step bodies execute at trace time only and contribute no
    #: equations).  Env override: ``WF_MONITORING_HEALTH`` (``''``/``'0'``
    #: off, anything else on); analyze with ``scripts/wf_health.py``.
    health: bool = False
    #: record the device-time split on every Nth SAMPLED service point
    #: (the pushes CompiledChain already times to completion); must be
    #: >= 1 when health is on — ``WF_HEALTH_SAMPLE`` overrides, the
    #: validator surfaces an illegal value as WF113 before the run
    health_sample: int = 1
    #: AOT-lower each freshly compiled program once more so its ``compile``
    #: journal record carries cost-analysis flops/bytes + the executable
    #: footprint.  That second lowering+compile runs inline in the driver
    #: loop, roughly doubling compile latency — turn it off for
    #: compile-heavy monitored runs (capacity/K ladders, autotune sweeps)
    #: where the cause/key/duration columns are enough
    health_cost_analysis: bool = True
    #: SLO sub-toggle (off by default): a declarative objective set over
    #: signals the snapshots already carry, evaluated as a per-SLO
    #: OK->WARN->PAGE state machine with fast/slow multi-window burn rates
    #: INSIDE every Reporter tick, plus automatic rate-limited incident
    #: bundles on PAGE (``observability/slo.py``).  Accepts ``True``
    #: (default spec set), a list of ``slo.SLOSpec``/dicts, or a JSON file
    #: path / inline JSON.  Host-side Reporter-thread work ONLY — compiled
    #: programs, operator state, and the perf-gate pins are byte-for-byte
    #: unchanged either way.  Env override: ``WF_SLO`` (``''``/``'0'`` off,
    #: ``'1'`` defaults, anything else a spec path / inline JSON); analyze
    #: with ``scripts/wf_slo.py``.
    slo: object = False
    #: minimum seconds between incident bundles + hard cap per run — the
    #: rate limit that keeps a restart storm from burying the host under
    #: forensics (``WF_SLO_COOLDOWN_S`` / ``WF_SLO_MAX_INCIDENTS``)
    slo_cooldown_s: float = 60.0
    slo_max_incidents: int = 8
    #: keep-last-N-lines retention for snapshots.jsonl (None = unlimited,
    #: today's behavior) — a long-running service's time series must not
    #: grow without bound; rotation is an atomic rewrite on the Reporter
    #: thread.  Env override: ``WF_SNAPSHOT_KEEP`` (``''``/``'0'`` =
    #: unlimited).
    snapshot_keep: Optional[int] = None
    #: fleet-telemetry sub-toggle (off by default): stream every Reporter
    #: tick's snapshot + journal delta as length-framed JSON to a
    #: ``FleetAggregator`` (``observability/fleet.py`` / ``scripts/
    #: wf_fleet.py serve``) through a BOUNDED drop-oldest outbox — a slow
    #: or dead aggregator costs frames (counted), never Reporter cadence.
    #: Accepts ``True`` (endpoint from ``WF_TELEMETRY_ENDPOINT``) or an
    #: endpoint string (``tcp://HOST:PORT`` / ``HOST:PORT`` /
    #: ``unix://PATH``).  Host-side Reporter-thread work ONLY — compiled
    #: programs, operator state, and the perf-gate pins are byte-for-byte
    #: unchanged either way.  Env override: ``WF_TELEMETRY`` (``''``/
    #: ``'0'`` off, ``'1'`` endpoint from WF_TELEMETRY_ENDPOINT, anything
    #: else IS the endpoint); a missing/unparseable endpoint or an outbox
    #: < 1 raises at Monitor construction and is WF117 in ``validate()``.
    telemetry: object = False
    #: bounded outbox depth between the Reporter tick and the telemetry
    #: sender thread (``WF_TELEMETRY_OUTBOX``; must be >= 1 — WF117)
    telemetry_outbox: int = 64
    #: self-driving remediation sub-toggle (off by default): a declarative
    #: :class:`~windflow_tpu.control.remediation.RemediationPolicy` mapping
    #: SLO burn signatures to the actuators the run owns (admission rate,
    #: autotuner re-climb, ...), evaluated on the Reporter tick right after
    #: the SLO verdicts (``SLOEngine.verdict_hook``) — so the incident
    #: bundle a PAGE commits records the actions the page triggered.
    #: Accepts ``True`` (default policy), a policy/action list, or a JSON
    #: file path / inline JSON.  REQUIRES the SLO engine: remediation on
    #: while ``slo`` resolves off is a construction-time ValueError (WF118
    #: pre-run).  Host-side Reporter-thread work ONLY — compiled programs,
    #: operator state, and the perf-gate pins are byte-for-byte unchanged
    #: either way.  Env override: ``WF_REMEDIATION`` (``''``/``'0'`` off,
    #: ``'1'`` default policy, anything else a policy path / inline JSON);
    #: analyze with ``scripts/wf_slo.py --report remediation``.
    remediation: object = False
    #: minimum seconds between remediation actions + hard cap per run (the
    #: incident-bundle rate-limit pattern) — ``WF_REMEDIATION_COOLDOWN_S``
    #: / ``WF_REMEDIATION_MAX_ACTIONS``.  The cooldown must be >= the
    #: reporter interval (a sub-tick cooldown cannot rate-limit anything
    #: — WF118, loud at construction)
    remediation_cooldown_s: float = 60.0
    remediation_max_actions: int = 8
    #: profile-on-page sub-toggle (off by default): a bounded
    #: ``jax.profiler`` capture window committed into every incident
    #: bundle BEFORE its manifest (``observability/profiling.py``) —
    #: device-side evidence for a latency PAGE.  Accepts ``True``
    #: (default window/cap), a :class:`~windflow_tpu.observability.
    #: profiling.ProfileConfig`, or ``False``.  REQUIRES the SLO engine
    #: (captures fire from PAGE entry only) and a capture window shorter
    #: than the reporter interval (the capture runs ON the Reporter tick
    #: thread) — both are construction-time ValueErrors here and WF120 in
    #: ``validate()``.  Every capture goes through the ONE
    #: ``stats.xprof_trace`` session guard; a held session is a
    #: ``profile_skipped`` reason inside the bundle, never a second
    #: latch.  Env override: ``WF_PROFILE`` (``''``/``'0'`` off) with
    #: ``WF_PROFILE_WINDOW_MS`` / ``WF_PROFILE_MAX_CAPTURES``; analyze
    #: with ``scripts/wf_profile.py``.
    profile: object = False

    def should_sample_e2e(self, n: int) -> bool:
        """THE e2e sampling policy, shared by every driver: every Nth source
        batch, never batch #1 — that one times JIT trace + XLA compile, not
        latency (the same exclusion as the chain's service sampling)."""
        return n > 0 and n % self.e2e_sample_every == 0

    @classmethod
    def resolve(cls, monitoring: Union[None, bool, str, "MonitoringConfig"],
                ) -> Optional["MonitoringConfig"]:
        """Normalize the user-facing ``monitoring=`` argument.

        ``None`` consults ``WF_MONITORING`` (``''``/``'0'`` = off, the same
        convention as ``WF_ORDERING_SKIP_SORTED``); ``False`` forces off;
        ``True`` = defaults; a string is the output directory; a config passes
        through. Returns None when monitoring is off."""
        if monitoring is False:
            return None
        if isinstance(monitoring, MonitoringConfig):
            cfg = monitoring
        elif isinstance(monitoring, str):
            cfg = cls(out_dir=monitoring)
        elif monitoring is True:
            cfg = cls()
        else:                              # None: env-driven
            env = os.environ.get("WF_MONITORING", "")
            if env in ("", "0"):
                return None
            cfg = cls() if env == "1" else cls(out_dir=env)
        iv = os.environ.get("WF_MONITORING_INTERVAL")
        if iv:
            cfg = dataclasses.replace(cfg, interval_s=float(iv))
        et = os.environ.get("WF_MONITORING_EVENT_TIME")
        if et is not None and et != "":
            cfg = dataclasses.replace(cfg, event_time=et != "0")
        hv = os.environ.get("WF_MONITORING_HEALTH")
        if hv is not None and hv != "":
            cfg = dataclasses.replace(cfg, health=hv != "0")
        hs = os.environ.get("WF_HEALTH_SAMPLE", "")
        if hs:
            cfg = dataclasses.replace(cfg, health_sample=int(hs))
        sv = os.environ.get("WF_SLO")
        if sv is not None and sv != "":
            cfg = dataclasses.replace(
                cfg, slo=(False if sv == "0"
                          else (True if sv == "1" else sv)))
        sc = os.environ.get("WF_SLO_COOLDOWN_S", "")
        if sc:
            cfg = dataclasses.replace(cfg, slo_cooldown_s=float(sc))
        sm = os.environ.get("WF_SLO_MAX_INCIDENTS", "")
        if sm:
            cfg = dataclasses.replace(cfg, slo_max_incidents=int(sm))
        sk = os.environ.get("WF_SNAPSHOT_KEEP", "")
        if sk:
            cfg = dataclasses.replace(
                cfg, snapshot_keep=(int(sk) if sk != "0" else None))
        tv = os.environ.get("WF_TELEMETRY")
        if tv is not None and tv != "":
            cfg = dataclasses.replace(
                cfg, telemetry=(False if tv == "0"
                                else (True if tv == "1" else tv)))
        te = os.environ.get("WF_TELEMETRY_ENDPOINT", "")
        if te and cfg.telemetry is True:
            # '1' (kwarg or env) defers the address to the endpoint var;
            # an explicit endpoint string always wins
            cfg = dataclasses.replace(cfg, telemetry=te)
        tb = os.environ.get("WF_TELEMETRY_OUTBOX", "")
        if tb:
            cfg = dataclasses.replace(cfg, telemetry_outbox=int(tb))
        rv = os.environ.get("WF_REMEDIATION")
        if rv is not None and rv != "":
            cfg = dataclasses.replace(
                cfg, remediation=(False if rv == "0"
                                  else (True if rv == "1" else rv)))
        rc = os.environ.get("WF_REMEDIATION_COOLDOWN_S", "")
        if rc:
            cfg = dataclasses.replace(cfg, remediation_cooldown_s=float(rc))
        rm = os.environ.get("WF_REMEDIATION_MAX_ACTIONS", "")
        if rm:
            cfg = dataclasses.replace(cfg, remediation_max_actions=int(rm))
        from . import profiling as _profiling
        prof = _profiling.resolve_profile(
            cfg.profile if cfg.profile is not False else None)
        cfg = dataclasses.replace(cfg, profile=prof if prof else False)
        if cfg.profile is not False:
            probs = _profiling.profile_problems(
                cfg.profile,
                slo_on=cfg.slo not in (False, None, "", "0"),
                interval_s=cfg.interval_s)
            # jax availability is a runtime/WF120 concern (serving hosts
            # legitimately resolve configs on jax-less boxes — every
            # capture just records profile_skipped); the structural
            # problems are construction-time errors like WF118
            probs = [p for p in probs if "not importable" not in p]
            if probs:
                raise ValueError(
                    "invalid profile-on-page config (the validator "
                    "reports these as WF120 before the run): "
                    + "; ".join(probs))
        if cfg.remediation not in (False, None, "", "0"):
            if cfg.slo in (False, None, "", "0"):
                raise ValueError(
                    "remediation=/WF_REMEDIATION is on but the SLO engine "
                    "(slo=/WF_SLO) resolves off — remediation consumes SLO "
                    "verdicts, so there is nothing to act on (the validator "
                    "reports this as WF118 before the run)")
            if float(cfg.remediation_cooldown_s) < float(cfg.interval_s):
                raise ValueError(
                    f"remediation_cooldown_s/WF_REMEDIATION_COOLDOWN_S "
                    f"({cfg.remediation_cooldown_s}) must be >= the reporter "
                    f"interval ({cfg.interval_s}s) — a sub-tick cooldown "
                    f"cannot rate-limit anything (WF118 before the run)")
            if int(cfg.remediation_max_actions) < 1:
                raise ValueError(
                    f"remediation_max_actions/WF_REMEDIATION_MAX_ACTIONS "
                    f"must be >= 1, got {cfg.remediation_max_actions} "
                    f"(WF118 before the run)")
        if cfg.snapshot_keep is not None and int(cfg.snapshot_keep) < 1:
            raise ValueError(
                f"snapshot_keep/WF_SNAPSHOT_KEEP must be >= 1 (or unset "
                f"for unlimited), got {cfg.snapshot_keep}")
        if cfg.health and int(cfg.health_sample) < 1:
            raise ValueError(
                f"health_sample/WF_HEALTH_SAMPLE must be >= 1, got "
                f"{cfg.health_sample} (the validator reports this as WF113 "
                f"before the run)")
        return cfg


def _telemetry_host_tag() -> str:
    """The host tag telemetry frames carry — the aggregator's merge key.
    ``WF_TELEMETRY_HOST`` (read at Monitor construction) overrides; else
    the multihost harness's ``jax.process_index()`` (the 2proc convention),
    falling back to the pid for processes without an initialized backend.
    Resolved only when telemetry is ON — the off path never touches jax."""
    tag = os.environ.get("WF_TELEMETRY_HOST", "")
    if tag:
        return tag
    try:
        import jax
        return f"host{jax.process_index()}"
    except Exception:  # noqa: BLE001 — no/broken backend: pid is still
        return f"pid{os.getpid()}"          # unique on one box


def event_time_enabled(monitoring=None) -> bool:
    """Resolve ONLY the event-time sub-toggle of a ``monitoring=`` argument
    — the chain-construction sites call this (the toggle sizes operator
    state, so it binds at build time; see ``MonitoringConfig.event_time``).
    Off whenever monitoring itself resolves off."""
    cfg = MonitoringConfig.resolve(monitoring)
    return bool(cfg is not None and cfg.event_time)


class Monitor:
    """Bundles registry + reporter + journal for one run and owns their
    lifecycle: ``start()`` launches the reporter thread and activates the
    journal; ``finish(target)`` stops the reporter (final snapshot), writes the
    topology dumps (``topology.dot`` / ``topology.json``), and closes the
    journal. ``finish`` is idempotent and runs in a ``finally`` inside the
    drivers, so no thread survives a failed run."""

    def __init__(self, config: MonitoringConfig, name: str = "pipegraph"):
        self.config = config
        os.makedirs(config.out_dir, exist_ok=True)
        #: runtime-health ledger (MonitoringConfig.health): activated for
        #: the run like the journal — CompiledChain/registry call sites
        #: reach it through device_health's module-level active hook
        self.health: Optional[device_health.HealthLedger] = (
            device_health.HealthLedger(
                sample_every=config.health_sample,
                cost_analysis=config.health_cost_analysis)
            if config.health else None)
        self.registry = MetricsRegistry(name, event_time=config.event_time,
                                        health_ledger=self.health)
        self.journal: Optional[EventJournal] = None
        journal_path = None
        if config.journal:
            journal_path = os.path.join(config.out_dir, "events.jsonl")
            self.journal = EventJournal(
                journal_path,
                flush_interval=config.journal_flush_interval)
        #: SLO engine (MonitoringConfig.slo): resolved here so a malformed
        #: spec set fails the run loudly at Monitor construction (the
        #: health_sample convention; validate() reports it as WF116
        #: pre-run), evaluated by the Reporter inside every tick
        self.slo: Optional[slo_engine.SLOEngine] = None
        specs = slo_engine.resolve_specs(config.slo)
        if specs:
            self.slo = slo_engine.SLOEngine(
                specs, out_dir=config.out_dir,
                cooldown_s=config.slo_cooldown_s,
                max_incidents=config.slo_max_incidents,
                journal_path=journal_path,
                fingerprint=self._config_fingerprint)
        #: profile-on-page (MonitoringConfig.profile): bound as the SLO
        #: engine's profiler hook so PAGE-entry incident captures commit a
        #: bounded device-profiler window (or its skip reason) into every
        #: bundle BEFORE the manifest.  Requires the SLO engine — profile
        #: on while slo resolves off is a construction-time ValueError
        #: (WF120 pre-run), mirroring remediation's WF118
        from . import profiling as _profiling
        prof_cfg = _profiling.resolve_profile(
            config.profile if config.profile is not False else None)
        if prof_cfg is not None:
            if self.slo is None:
                raise ValueError(
                    "profile=/WF_PROFILE is on but the SLO engine "
                    "(slo=/WF_SLO) is off — captures fire from PAGE entry "
                    "only, so there is nothing to trigger them (WF120 "
                    "before the run)")
            self.slo.profiler = _profiling.ProfileOnPage(prof_cfg)
        #: remediation engine (MonitoringConfig.remediation): resolved here
        #: so an unusable policy fails the run loudly at Monitor
        #: construction (the SLO-engine convention; validate() reports it
        #: as WF118 pre-run).  Subscribed to the SLO engine's per-tick
        #: verdicts; the drivers bind the actuators the run actually owns
        #: in run() (an unbound actuator skips loudly, never guesses)
        self.remediation = None
        from ..control import remediation as _remediation
        policy = _remediation.resolve_policy(config.remediation)
        if policy is not None:
            if self.slo is None:
                raise ValueError(
                    "remediation=/WF_REMEDIATION is on but the SLO engine "
                    "(slo=/WF_SLO) is off — remediation consumes SLO "
                    "verdicts (WF118 before the run)")
            probs = _remediation.policy_problems(
                policy, [s.name for s in specs])
            if probs:
                raise ValueError(
                    "invalid remediation policy (the validator reports "
                    "these as WF118 before the run): " + "; ".join(probs))
            self.remediation = _remediation.RemediationEngine(
                policy, cooldown_s=config.remediation_cooldown_s,
                max_actions=config.remediation_max_actions)
            self.slo.verdict_hook = self.remediation.on_verdicts
            self.slo.remediation = self.remediation
        #: fleet telemetry agent (MonitoringConfig.telemetry): constructed
        #: here so a missing/unparseable endpoint or an outbox < 1 fails
        #: the run loudly at Monitor construction (the SLO-engine
        #: convention; validate() reports it as WF117 pre-run).  The
        #: Reporter stamps its stats into every snapshot and offers the
        #: written snapshot after each tick — never blocking (fleet.py)
        self.telemetry = None
        if config.telemetry not in (False, None):
            from . import fleet
            endpoint = (config.telemetry
                        if isinstance(config.telemetry, str)
                        else os.environ.get("WF_TELEMETRY_ENDPOINT", ""))
            self.telemetry = fleet.TelemetryAgent(
                endpoint, host=_telemetry_host_tag(),
                out_dir=config.out_dir,
                outbox=config.telemetry_outbox,
                journal_path=journal_path, journal=self.journal)
        self.reporter = Reporter(self.registry, config.out_dir,
                                 interval_s=config.interval_s,
                                 prometheus=config.prometheus,
                                 slo_engine=self.slo,
                                 snapshot_keep=config.snapshot_keep,
                                 telemetry_agent=self.telemetry)
        self._finished = False

    def _config_fingerprint(self) -> dict:
        """Chain signatures for an incident bundle's config.json — WHICH
        compiled programs were live when the SLO paged (the TuningCache
        keying reused as provenance; the env half lives in slo.py)."""
        try:
            from ..control.autotune import chain_signature
        except ImportError:
            return {}
        sigs = []
        for ch in self.registry._iter_health_chains():
            try:
                sigs.append(chain_signature(ch.ops))
            except Exception:   # noqa: BLE001 — a half-built chain must not
                continue        # kill the incident capture
        return {"chain_signatures": sigs} if sigs else {}

    def start(self) -> None:
        if self.journal is not None:
            set_journal(self.journal)
            self.journal.event("monitoring_start", graph=self.registry.name,
                               interval_s=self.config.interval_s)
        if self.health is not None:
            device_health.set_active(self.health)
        if self.telemetry is not None:
            self.telemetry.start()
        self.reporter.start()

    def finish(self, target=None) -> None:
        if self._finished:
            return
        self._finished = True
        try:
            self.reporter.stop(final=True)
            if target is not None:
                snap = self.registry.snapshot()
                with open(os.path.join(self.config.out_dir,
                                       "topology.dot"), "w") as f:
                    f.write(topology_dot(target, snap))
                import json as _json
                with open(os.path.join(self.config.out_dir,
                                       "topology.json"), "w") as f:
                    _json.dump(topology_json(target, snap), f, indent=1)
        finally:
            if self.telemetry is not None:
                # AFTER reporter.stop: the final emit's frame gets its
                # best-effort flush window before the sender goes away
                self.telemetry.close()
            if (self.health is not None
                    and device_health.get_active() is self.health):
                device_health.set_active(None)
            if self.journal is not None:
                self.journal.event("monitoring_end",
                                   graph=self.registry.name)
                if journal.get_active() is self.journal:
                    set_journal(None)
                self.journal.close()
