"""SLO engine — burn-rate alerting, health states, incident forensics.

The PR 1/5/9/10 observability stack *records* (latency histograms, causal
traces, the watermark map, the runtime-health ledger) but never *judges*: an
``[OVERFLOW-RISK]`` flag exists only when a human runs ``wf_state.py`` after
the fact.  This module closes that loop on the Reporter thread — the
host-side seat where the whole control loop already lives (the GPU-First
stance of arXiv:2306.11686 applied to monitoring: the judgment runs where
the telemetry is, not in a human's terminal hours later):

- :class:`SLOSpec` — a declarative objective over a **signal** the metrics
  snapshots already carry (``SIGNALS``: e2e/service p99 latency, watermark
  freshness, drop ratio, recovery time, HBM headroom, unexpected-retrace
  rate), with a target, an error-budget ``objective``, and **fast/slow
  multi-window burn-rate** thresholds — a transient spike fills the fast
  window and WARNs; only a burn sustained across the slow window PAGEs.
- :class:`SLOEngine` — per-SLO OK -> WARN -> PAGE -> OK state machine
  evaluated once per Reporter tick (``observe(snap)`` folds a ``"slo"``
  section into the snapshot the Reporter is about to write).  PAGE entry
  journals ``slo_page``; return to OK journals ``slo_recover``.  PAGE is
  sticky until the FAST window is clean (``burn_fast < warn_burn``) — the
  slow window keeps history that would otherwise hold a recovered SLO
  hostage for ``slow_window`` ticks.
- **Incident forensics** — a PAGE transition captures an atomic,
  rate-limited (cooldown + max-per-run) bundle under
  ``<out_dir>/incidents/<stamp>_<slo>/``: the flight-recorder Chrome trace
  (when tracing is on), the journal tail, the latest health / shards /
  event-time snapshot sections, the SLO's burn timeline, and a config
  fingerprint (``WF_*`` env + chain signatures).  Every artifact is written
  via the hardened tmp+fsync+rename discipline and ``manifest.json`` is
  written LAST — the manifest IS the commit point, so a crash mid-capture
  leaves a manifest-less directory that readers report as torn, never a
  half-bundle that parses.
- **Offline evaluation** (:func:`evaluate_series`) — the same burn/state
  math over any ``snapshots.jsonl``; ``scripts/wf_slo.py`` builds its
  report and its 0/1/2 exit contract on it.

Everything is off by default behind ``MonitoringConfig.slo`` (``WF_SLO``,
the established ``kwarg=``/``WF_*`` convention).  The engine is host-side
Reporter-thread work ONLY: compiled programs, operator state, checkpoint
layouts, and the perf-gate pins are byte-for-byte unchanged either way
(``tests/test_slo.py`` pins the four-driver result identity and the HLO
identity).

This module must stay importable WITHOUT jax at module scope:
``scripts/wf_slo.py`` / ``wf_state.py`` / ``wf_health.py`` load it by file
path (the ``event_time.py``/``device_health.py`` convention) to reuse the
burn math and the bundle readers on any box the artifacts were copied to.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from . import journal as _journal

#: health states, worst-last (the merge folds per-SLO state by code MAX)
STATE_OK, STATE_WARN, STATE_PAGE = "ok", "warn", "page"
_STATE_CODE = {STATE_OK: 0, STATE_WARN: 1, STATE_PAGE: 2}
_CODE_STATE = {v: k for k, v in _STATE_CODE.items()}

#: journal-tail lines captured into an incident bundle
_JOURNAL_TAIL_LINES = 256


def _atomic_write(path: str, data: str) -> None:
    """The Reporter's hardened write-then-rename discipline (unique tmp +
    fsync + ``os.replace``), duplicated here so the module stays loadable
    by file path without dragging ``reporter.py``/``metrics.py`` into the
    stdlib CLIs' synthetic package."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


# ----------------------------------------------------------------- signals
#
# Each signal is a pure function of (latest snapshot, previous snapshot) ->
# Optional[float]: None means "no observation this tick" (the sub-system is
# off or saw no traffic), which neither violates nor clears the SLO — the
# burn windows simply do not advance.  Counters are cumulative in the
# snapshots, so rate/ratio signals difference against the previous tick.


def _sig_e2e_p99_ms(snap, prev) -> Optional[float]:
    sec = snap.get("e2e_latency_us") or {}
    if "samples_tick" in sec:            # windowed form (metrics.py >= PR15)
        if not sec["samples_tick"]:
            return None                  # no traffic this tick
        return float(sec.get("p99_tick", 0.0)) / 1e3
    if not sec.get("samples"):
        return None
    return float(sec.get("p99", 0.0)) / 1e3


def _sig_service_p99_ms(snap, prev) -> Optional[float]:
    vals = [row["service_time_us"]["p99"] for row in snap.get("operators", [])
            if (row.get("service_time_us") or {}).get("samples")]
    if not vals:
        return None
    return float(max(vals)) / 1e3


def _sig_watermark_lag(snap, prev) -> Optional[float]:
    """Event-time freshness: the widest arrived-but-unfired span over every
    operator carrying a frontier (event-time sections when the sub-toggle is
    on, the TB watermark gauge otherwise)."""
    vals = []
    for row in snap.get("operators", []):
        sec = row.get("event_time") or {}
        if "lag" in sec:
            vals.append(sec["lag"])
        elif (row.get("watermark") or {}).get("lag_ts") is not None:
            vals.append(row["watermark"]["lag_ts"])
    if not vals:
        return None
    return float(max(vals))


def _drop_total(snap) -> float:
    tot = float((snap.get("totals") or {}).get("tuples_dropped_old", 0))
    for row in snap.get("operators", []):
        for k, v in (row.get("counters") or {}).items():
            if k in ("overflow_drops", "match_drops", "arch_drops"):
                tot += v
    ctl = (snap.get("control") or {}).get("counters") or {}
    return tot + float(ctl.get("shed_tuples", 0))


def _offered_total(snap) -> float:
    ctl = (snap.get("control") or {}).get("counters") or {}
    off = float(ctl.get("admitted_tuples", 0)) + float(ctl.get("shed_tuples",
                                                               0))
    if off > 0:
        return off
    # no admission control in the run: the widest per-operator input count
    # is the honest stream-size stand-in (sources count their tuples there)
    vals = [row.get("inputs_received", 0) for row in snap.get("operators",
                                                              [])]
    return float(max(vals)) if vals else 0.0


def _sig_drop_ratio(snap, prev) -> Optional[float]:
    d1, o1 = _drop_total(snap), _offered_total(snap)
    d0, o0 = (_drop_total(prev), _offered_total(prev)) if prev else (0.0, 0.0)
    offered = o1 - o0
    if offered <= 0:
        return None                      # no traffic this tick
    return max(d1 - d0, 0.0) / offered


def _sig_recovery_s(snap, prev) -> Optional[float]:
    """Seconds spent inside supervisor/shard restore spans during this tick
    (the cumulative ``recovery_seconds`` counter the supervisors bump around
    every restore, differenced per tick)."""
    rec = snap.get("recovery")
    if rec is None or "recovery_seconds" not in rec:
        return None
    now = float(rec.get("recovery_seconds", 0.0))
    before = float(((prev or {}).get("recovery") or {})
                   .get("recovery_seconds", 0.0))
    return max(now - before, 0.0)


def _sig_hbm_headroom_pct(snap, prev) -> Optional[float]:
    vals = []
    for d in (snap.get("health") or {}).get("devices", []):
        head, limit = d.get("headroom_bytes"), d.get("bytes_limit")
        if head is not None and limit:
            vals.append(100.0 * head / limit)
    return min(vals) if vals else None


def _sig_retrace_rate(snap, prev) -> Optional[float]:
    comp = (snap.get("health") or {}).get("compile")
    if comp is None:
        return None
    now = float(comp.get("retraces_unexpected", 0))
    before = float((((prev or {}).get("health") or {}).get("compile") or {})
                   .get("retraces_unexpected", 0))
    return max(now - before, 0.0)


#: THE signal registry: name -> (extractor, default mode).  ``"max"``
#: violates when signal > target (latency, drops, lag); ``"min"`` when
#: signal < target (headroom).  An unknown name is a WF116 validator error.
SIGNALS: Dict[str, Tuple[Callable, str]] = {
    "e2e_p99_ms": (_sig_e2e_p99_ms, "max"),
    "service_p99_ms": (_sig_service_p99_ms, "max"),
    "watermark_lag": (_sig_watermark_lag, "max"),
    "drop_ratio": (_sig_drop_ratio, "max"),
    "recovery_s": (_sig_recovery_s, "max"),
    "hbm_headroom_pct": (_sig_hbm_headroom_pct, "min"),
    "retrace_rate": (_sig_retrace_rate, "max"),
}


def _tenant_row(snap, tenant: str) -> Optional[dict]:
    """One tenant's counter row from the serving section (``serving/
    tenants.py`` TenantRegistry.counters -> snapshot ``serving.tenants``)."""
    if snap is None:
        return None
    return ((snap.get("serving") or {}).get("tenants") or {}).get(tenant)


def _sig_tenant_drop_ratio(snap, prev, tenant: str) -> Optional[float]:
    """Per-tick shed fraction of ONE tenant's offered batches — the
    isolation signal: a noisy tenant's shedding moves ONLY the SLOs
    labelled with its id, a quiet neighbor's stays 0."""
    row = _tenant_row(snap, tenant)
    if row is None:
        return None
    prow = _tenant_row(prev, tenant) or {}
    offered = float(row.get("offered", 0)) - float(prow.get("offered", 0))
    if offered <= 0:
        return None                      # no traffic from this tenant
    shed = float(row.get("shed", 0)) - float(prow.get("shed", 0))
    return max(shed, 0.0) / offered


def _sig_tenant_shed_tuples(snap, prev, tenant: str) -> Optional[float]:
    """Tuples one tenant lost to shedding this tick (absolute pressure —
    the remediation gate's coordinate when ratios are too coarse)."""
    row = _tenant_row(snap, tenant)
    if row is None:
        return None
    prow = _tenant_row(prev, tenant) or {}
    return max(float(row.get("shed_tuples", 0))
               - float(prow.get("shed_tuples", 0)), 0.0)


def _sig_tenant_e2e_p99_ms(snap, prev, tenant: str) -> Optional[float]:
    """One tenant's wire-to-sink p99 over the LAST TICK's samples
    (``serving.tenants`` ``e2e_p99_tick_ms`` — the windowed form, the
    ``_sig_e2e_p99_ms`` discipline: a cumulative p99 could never recover
    below target once a stall pushed the whole-run percentile over it).
    None when the tenant sent no traffic this tick (or latency sampling is
    off), which neither violates nor clears — the burn windows hold."""
    row = _tenant_row(snap, tenant)
    if row is None:
        return None
    if "e2e_samples_tick" in row:
        if not row["e2e_samples_tick"]:
            return None                  # no traffic from this tenant
        return float(row.get("e2e_p99_tick_ms", 0.0))
    if not row.get("e2e_samples"):
        return None                      # latency never sampled
    return float(row.get("e2e_p99_ms", 0.0))


#: tenant-labelled signal family (the serving plane's label dimension):
#: name -> (extractor(snap, prev, tenant), default mode).  A spec using one
#: of these MUST carry ``tenant=`` (and a host signal must NOT) — enforced
#: by spec_problems (WF116) and cross-checked against the declared tenant
#: ids by the serving validator (WF119).
TENANT_SIGNALS: Dict[str, Tuple[Callable, str]] = {
    "tenant_drop_ratio": (_sig_tenant_drop_ratio, "max"),
    "tenant_shed_tuples": (_sig_tenant_shed_tuples, "max"),
    "tenant_e2e_p99_ms": (_sig_tenant_e2e_p99_ms, "max"),
}


# -------------------------------------------------------------------- specs


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective over a snapshot signal.

    The error budget is ``1 - objective`` (the fraction of ticks allowed to
    violate ``target``).  Burn rate over a window = (violating fraction of
    the window) / budget, so burn 1.0 spends the budget exactly on pace and
    burn ``1/(1-objective)`` means EVERY tick violates.  The two windows
    implement the standard multi-window multi-burn discipline: WARN when the
    fast window burns >= ``warn_burn`` (a spike — worth a look, not a
    wake-up), PAGE only when BOTH windows burn >= ``page_burn`` (the spike
    is sustained)."""

    name: str
    signal: str
    target: float
    #: fraction of ticks that must meet the target (budget = 1 - objective)
    objective: float = 0.9
    #: window lengths in Reporter ticks over the snapshots.jsonl cadence
    fast_window: int = 5
    slow_window: int = 60
    warn_burn: float = 1.0
    page_burn: float = 2.0
    #: violation sense; None = the signal's default (SIGNALS)
    mode: Optional[str] = None
    #: tenant label (serving plane): REQUIRED for TENANT_SIGNALS — the
    #: extractor then reads this tenant's ``serving.tenants`` row only, so
    #: one noisy tenant pages its own SLO without touching its neighbors'
    #: budgets; must be None for host-level SIGNALS
    tenant: Optional[str] = None

    def resolved_mode(self) -> str:
        if self.mode is not None:
            return self.mode
        sig = SIGNALS.get(self.signal) or TENANT_SIGNALS.get(self.signal)
        return sig[1] if sig else "max"

    def violated(self, value: float) -> bool:
        if self.resolved_mode() == "min":
            return value < float(self.target)
        return value > float(self.target)

    def budget(self) -> float:
        return max(1.0 - float(self.objective), 1e-9)


def spec_problems(spec: SLOSpec) -> List[str]:
    """Every reason this spec cannot be honored — THE shared legality check
    of the engine constructor, the WF116 validator, and ``wf_lint
    --explain WF116``'s story.  Empty list = clean."""
    out = []
    if not spec.name or not str(spec.name).strip():
        out.append("spec has an empty name")
    if spec.signal not in SIGNALS and spec.signal not in TENANT_SIGNALS:
        out.append(f"unknown signal {spec.signal!r} — registered signals: "
                   f"{', '.join(sorted(SIGNALS))}; tenant signals: "
                   f"{', '.join(sorted(TENANT_SIGNALS))}")
    if spec.signal in TENANT_SIGNALS and spec.tenant is None:
        out.append(f"signal {spec.signal!r} is tenant-labelled but the spec "
                   f"carries no tenant= — the extractor needs ONE tenant's "
                   f"serving.tenants row to read")
    if spec.signal in SIGNALS and spec.tenant is not None:
        out.append(f"tenant={spec.tenant!r} on host-level signal "
                   f"{spec.signal!r} — host signals carry no tenant "
                   f"dimension (tenant signals: "
                   f"{', '.join(sorted(TENANT_SIGNALS))})")
    if int(spec.fast_window) < 1:
        out.append(f"fast_window must be >= 1, got {spec.fast_window}")
    if int(spec.fast_window) >= int(spec.slow_window):
        out.append(f"fast_window ({spec.fast_window}) must be < slow_window "
                   f"({spec.slow_window}) — the fast window detects the "
                   f"spike, the slow window confirms the sustained burn")
    if not (0.0 < float(spec.objective) < 1.0):
        out.append(f"objective must be in (0, 1), got {spec.objective}")
    if float(spec.warn_burn) <= 0 or float(spec.page_burn) <= 0:
        out.append("warn_burn/page_burn must be > 0")
    if float(spec.warn_burn) > float(spec.page_burn):
        out.append(f"warn_burn ({spec.warn_burn}) must be <= page_burn "
                   f"({spec.page_burn}) — WARN is the earlier threshold")
    if spec.mode is not None and spec.mode not in ("max", "min"):
        out.append(f"mode must be 'max' or 'min', got {spec.mode!r}")
    return out


def default_specs() -> List[SLOSpec]:
    """The ``slo=True`` / ``WF_SLO=1`` spec set: conservative defaults over
    every signal family the snapshots carry (signals whose sub-system is off
    simply never observe — their SLO idles at OK)."""
    return [
        SLOSpec("latency_e2e", "e2e_p99_ms", target=250.0),
        SLOSpec("freshness", "watermark_lag", target=1e6),
        SLOSpec("drops", "drop_ratio", target=0.01),
        SLOSpec("recovery", "recovery_s", target=1.0),
        SLOSpec("hbm_headroom", "hbm_headroom_pct", target=10.0),
        SLOSpec("retraces", "retrace_rate", target=0.0),
    ]


def _spec_from_dict(d: dict) -> SLOSpec:
    allowed = {f.name for f in dataclasses.fields(SLOSpec)}
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(f"unknown SLOSpec field(s) {sorted(unknown)} "
                         f"(allowed: {sorted(allowed)})")
    if "name" not in d or "signal" not in d or "target" not in d:
        raise ValueError(f"an SLO spec needs at least name/signal/target, "
                         f"got {sorted(d)}")
    return SLOSpec(**d)


def resolve_specs(slo) -> Optional[List[SLOSpec]]:
    """Normalize the ``MonitoringConfig.slo`` value (after its ``WF_SLO``
    env resolution) into a spec list: ``False``/``None``/``''``/``'0'`` =
    off (None), ``True``/``'1'`` = :func:`default_specs`, a list/tuple of
    ``SLOSpec``/dicts passes through, a string is inline JSON (when it
    starts with ``[``/``{``) or a JSON file path.  JSON top level: a list of
    spec dicts, or ``{"specs": [...]}``.  Raises ``ValueError`` on malformed
    input — surfaced pre-run as WF116."""
    if slo is None or slo is False:
        return None
    if slo is True:
        return default_specs()
    if isinstance(slo, str):
        s = slo.strip()
        if s in ("", "0"):
            return None
        if s == "1":
            return default_specs()
        if s.startswith("[") or s.startswith("{"):
            data = json.loads(s)
        else:
            with open(s) as f:
                data = json.load(f)
        if isinstance(data, dict):
            data = data.get("specs")
        if not isinstance(data, list):
            raise ValueError(f"SLO spec JSON must be a list of spec objects "
                             f"(or {{'specs': [...]}}), got "
                             f"{type(data).__name__}")
        return [_spec_from_dict(dict(d)) for d in data]
    if isinstance(slo, (list, tuple)):
        out = []
        for item in slo:
            if isinstance(item, SLOSpec):
                out.append(item)
            elif isinstance(item, dict):
                out.append(_spec_from_dict(dict(item)))
            else:
                raise ValueError(f"slo entries must be SLOSpec or dict, got "
                                 f"{type(item).__name__}")
        return out or None
    raise ValueError(f"slo= accepts None/bool/str/list, got "
                     f"{type(slo).__name__}")


# ------------------------------------------------------------- the engine


class _SLOState:
    """Per-SLO evaluation state: the violation window, the health state, and
    the bounded burn/state history the incident bundle snapshots."""

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        # newest-last violation booleans; the slow window bounds retention
        self.window: Deque[bool] = collections.deque(
            maxlen=int(spec.slow_window))
        self.state = STATE_OK
        self.pages = 0
        self.last_value: Optional[float] = None
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        #: (tick, value, burn_fast, burn_slow, state) — the burn timeline
        self.history: Deque[tuple] = collections.deque(
            maxlen=int(spec.slow_window))
        #: (tick, from_state, to_state) transitions, whole-run
        self.transitions: List[tuple] = []

    def _burn(self, w: int) -> float:
        vals = list(self.window)[-w:]
        # fixed denominator: a window that has not filled yet under-reports
        # (conservative — a 2-tick-old run cannot page off 2 samples)
        return round((sum(vals) / float(w)) / self.spec.budget(), 4)

    def row(self) -> dict:
        out = {"state": self.state, "code": _STATE_CODE[self.state],
               "burn_fast": self.burn_fast, "burn_slow": self.burn_slow,
               "signal": self.last_value, "target": self.spec.target,
               "pages": self.pages}
        if self.spec.tenant is not None:
            # the serving label dimension: wf_top's tenants panel and the
            # fleet fold join SLO state to tenant rows on this key
            out["tenant"] = self.spec.tenant
        return out


class SLOEngine:  # wf-lint: single-writer[reporter, driver]
    """Evaluates a spec set once per Reporter tick and owns incident
    capture.  Single-writer by construction (the class-level annotation's
    rationale): ``observe`` runs on the Reporter tick thread while the run
    is live, and on the driver thread only for the final ``stop()`` emit —
    which the Reporter issues strictly AFTER joining the tick thread (the
    ``Reporter.ticks`` discipline)."""

    def __init__(self, specs: Sequence[SLOSpec], out_dir: Optional[str],
                 cooldown_s: float = 60.0, max_incidents: int = 8,
                 journal_path: Optional[str] = None,
                 fingerprint: Optional[Callable[[], dict]] = None,
                 journal: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        specs = list(specs or [])
        if not specs:
            raise ValueError("SLOEngine needs at least one SLOSpec")
        problems = []
        seen = set()
        for s in specs:
            problems += [f"slo[{s.name}]: {p}" for p in spec_problems(s)]
            if s.name in seen:
                problems.append(f"slo[{s.name}]: duplicate SLO name")
            seen.add(s.name)
        if problems:
            raise ValueError("invalid SLO spec set (the validator reports "
                             "these as WF116 before the run): "
                             + "; ".join(problems))
        self.specs = specs
        self.out_dir = out_dir
        self.cooldown_s = float(cooldown_s)
        self.max_incidents = int(max_incidents)
        self.journal_path = journal_path
        self.fingerprint = fingerprint
        self.journal = bool(journal)
        self._clock = clock
        self._states = [_SLOState(s) for s in specs]
        self._prev: Optional[dict] = None
        self._tick = 0
        self.incidents_captured = 0
        self.incidents_suppressed = 0
        self._last_capture: Optional[float] = None
        #: when set (an EventJournal), transition events go to THIS journal
        #: instead of the process-global active one — the fleet aggregator
        #: runs an engine over the MERGED view inside a process that may
        #: also be a monitored host, and its fleet pages must land in the
        #: fleet events.jsonl, never the co-resident host's
        self.journal_sink = None
        #: per-tick verdict subscriber (``fn(snap)``), called AFTER the
        #: ``"slo"`` section is folded and BEFORE incident capture — the
        #: remediation engine (control/remediation.py) rides here, so the
        #: actions it takes on the triggering tick land inside the
        #: triggering bundle.  Same thread as observe (Reporter); a hook
        #: failure is recorded on the snapshot, never kills the tick
        self.verdict_hook = None
        #: the bound RemediationEngine (or None): duck-typed — incident
        #: capture asks it for ``section()`` to commit ``remediation.json``
        #: into every bundle before the manifest
        self.remediation = None
        #: profile-on-page hook (or None): ``fn(dir) -> dict`` run at
        #: capture time (``observability/profiling.py`` ProfileOnPage) —
        #: the returned summary (a capture manifest or a recorded
        #: ``profile_skipped`` reason) commits as ``profile.json`` BEFORE
        #: the bundle manifest, with the raw capture under ``<bundle>/
        #: profile/``.  Same verdict_hook wiring convention (Monitor binds
        #: it); same thread (Reporter tick); must never raise
        self.profiler = None
        self._incoming_slo = None

    # -- evaluation --------------------------------------------------------

    def observe(self, snap: dict) -> dict:
        """One tick: extract every signal, advance the burn windows, run the
        state machines, journal transitions, run the verdict hook, capture
        incidents on PAGE entry, and fold the ``"slo"`` section into
        ``snap`` (returned)."""
        self._tick += 1
        sec: Dict[str, dict] = {}
        paged = []
        #: the slo section as the snapshot ARRIVED (the merged host fold on
        #: a fleet aggregator — carries worst_host/pages_by_host).  Capture
        #: used to run before the ``snap["slo"] = sec`` fold and read it
        #: from snap directly; now that the verdict hook runs in between,
        #: subclasses (FleetSLOEngine.correlation) read it from here
        self._incoming_slo = snap.get("slo")
        for st in self._states:
            spec = st.spec
            if spec.signal in TENANT_SIGNALS:
                extractor, _mode = TENANT_SIGNALS[spec.signal]
                value = extractor(snap, self._prev, spec.tenant)
            else:
                extractor, _mode = SIGNALS[spec.signal]
                value = extractor(snap, self._prev)
            if value is not None:
                st.last_value = round(float(value), 6)
                st.window.append(spec.violated(value))
                st.burn_fast = st._burn(int(spec.fast_window))
                st.burn_slow = st._burn(int(spec.slow_window))
                if self._step_state(st, snap):
                    paged.append(st)
            st.history.append((self._tick, st.last_value, st.burn_fast,
                               st.burn_slow, st.state))
            sec[spec.name] = st.row()
        snap["slo"] = sec
        # verdict hook BEFORE capture: remediation acts on this tick's
        # verdicts first, so the bundle a PAGE is about to commit records
        # the actions the page itself triggered
        if self.verdict_hook is not None:
            try:
                self.verdict_hook(snap)
            except Exception as e:  # noqa: BLE001 — a broken hook must not
                # kill the tick, and must not die silently: the snapshot
                # carries the error (the slo_error convention)
                snap["remediation_error"] = f"{type(e).__name__}: {e}"
        for st in paged:
            self._maybe_capture(st, snap)
        self._prev = snap
        return snap

    def _step_state(self, st: _SLOState, snap: dict) -> bool:
        """Advance one SLO's state machine; returns True on PAGE entry (the
        caller captures the incident AFTER the verdict hook has run)."""
        spec = st.spec
        before = st.state
        if st.state == STATE_PAGE:
            # sticky until the FAST window is clean — recovery must be
            # recent, not merely diluted across the slow window
            if st.burn_fast < spec.warn_burn:
                st.state = STATE_OK
        else:
            if (st.burn_fast >= spec.page_burn
                    and st.burn_slow >= spec.page_burn):
                st.state = STATE_PAGE
            elif st.burn_fast >= spec.warn_burn:
                st.state = STATE_WARN
            else:
                st.state = STATE_OK
        if st.state == before:
            return False
        st.transitions.append((self._tick, before, st.state))
        if st.state == STATE_PAGE:
            st.pages += 1
            if self.journal:
                self._record("slo_page", slo=spec.name,
                             signal=spec.signal, value=st.last_value,
                             target=spec.target, burn_fast=st.burn_fast,
                             burn_slow=st.burn_slow, tick=self._tick)
            return True
        if st.state == STATE_OK and self.journal:
            self._record("slo_recover", slo=spec.name,
                         from_state=before, burn_fast=st.burn_fast,
                         burn_slow=st.burn_slow, tick=self._tick)
        return False

    def _record(self, name: str, **fields) -> None:
        if self.journal_sink is not None:
            self.journal_sink.event(name, **fields)
        else:
            _journal.record(name, **fields)

    def report(self) -> Dict[str, dict]:
        """Whole-run summary per SLO (the offline CLI's data model): the
        latest row plus the transition timeline, burn history, and the
        burning verdict (state != ok)."""
        out = {}
        for st in self._states:
            row = st.row()
            row["burning"] = st.state != STATE_OK
            row["transitions"] = [
                {"tick": t, "from": a, "to": b}
                for (t, a, b) in st.transitions]
            row["history"] = [
                {"tick": t, "value": v, "burn_fast": bf, "burn_slow": bs,
                 "state": s} for (t, v, bf, bs, s) in st.history]
            row["signal_name"] = st.spec.signal
            out[st.spec.name] = row
        return out

    # -- incident capture --------------------------------------------------

    def _maybe_capture(self, st: _SLOState, snap: dict) -> None:
        if self.out_dir is None:
            return
        now = self._clock()
        if self.incidents_captured >= self.max_incidents or (
                self._last_capture is not None
                and now - self._last_capture < self.cooldown_s):
            # rate limit: a restart storm re-paging every few ticks must not
            # bury the host under bundles — the journal still carries every
            # slo_page, so nothing is lost, only the forensics dedup'd
            self.incidents_suppressed += 1
            return
        try:
            self.capture_incident(st, snap)
        except OSError:
            return                        # disk trouble: never kill a tick
        self.incidents_captured += 1
        self._last_capture = now

    def capture_incident(self, st: _SLOState, snap: dict) -> str:
        """Write one forensic bundle for a paging SLO.  Every artifact goes
        through :func:`_atomic_write`; ``manifest.json`` lands LAST and is
        the commit point — a reader (``list_incidents``) treats a
        manifest-less directory as torn and never half-parses it."""
        stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime())
        name = f"{stamp}_t{self._tick}_{st.spec.name}"
        d = os.path.join(self.out_dir, "incidents", name)
        os.makedirs(d, exist_ok=True)
        files = []

        def put(fname: str, data: dict) -> None:
            _atomic_write(os.path.join(d, fname),
                          json.dumps(data, indent=1, sort_keys=True,
                                     default=str))
            files.append(fname)

        # the snapshot sections the post-mortem starts from
        put("sections.json", {
            "slo": snap.get("slo") or {k.spec.name: k.row()
                                       for k in self._states},
            "health": snap.get("health"),
            "shards": snap.get("shards"),
            "event_time": snap.get("event_time"),
            "e2e_latency_us": snap.get("e2e_latency_us"),
            "recovery": snap.get("recovery"),
            "queues": snap.get("queues"),
        })
        put("burn.json", {
            "slo": st.spec.name, "spec": dataclasses.asdict(st.spec),
            "timeline": [{"tick": t, "value": v, "burn_fast": bf,
                          "burn_slow": bs, "state": s}
                         for (t, v, bf, bs, s) in st.history],
            "transitions": [{"tick": t, "from": a, "to": b}
                            for (t, a, b) in st.transitions],
        })
        tail = self._journal_tail()
        if tail is not None:
            _atomic_write(os.path.join(d, "journal_tail.jsonl"), tail)
            files.append("journal_tail.jsonl")
        chrome = self._chrome_dump(tail)
        if chrome is not None:
            put("trace.json", chrome)
        put("config.json", self._config_fingerprint())
        if self.remediation is not None:
            # the action ledger as of THIS tick — the verdict hook ran
            # before capture, so the bundle records what the page triggered
            put("remediation.json", self.remediation.section())
        for fname, data in sorted(self._extra_bundle_files(st, snap).items()):
            put(fname, data)
        if self.profiler is not None:
            # profile-on-page: the bounded device capture (or its recorded
            # skip reason) commits BEFORE the manifest, so a committed
            # bundle either carries on-device evidence or says why not
            try:
                prof = self.profiler(os.path.join(d, "profile"))
            except Exception as e:  # noqa: BLE001 — forensics must never
                # kill the tick; ProfileOnPage already catches, this is the
                # belt for a user-supplied hook
                prof = {"profile_skipped": f"{type(e).__name__}: {e}"}
            put("profile.json", prof)
        # manifest LAST — the commit point
        _atomic_write(os.path.join(d, "manifest.json"), json.dumps({
            "schema": 1, "slo": st.spec.name, "signal": st.spec.signal,
            "state": st.state, "value": st.last_value,
            "target": st.spec.target, "burn_fast": st.burn_fast,
            "burn_slow": st.burn_slow, "tick": self._tick,
            "wall": time.time(), "files": files,
        }, indent=1, sort_keys=True))
        return d

    def _extra_bundle_files(self, st: _SLOState, snap: dict) -> dict:
        """Subclass hook: extra ``{filename: json-serializable}`` artifacts
        committed into the bundle BEFORE the manifest (so the manifest's
        ``files`` list covers them).  The base engine adds none; the fleet
        aggregator's engine adds ``correlation.json`` (which hosts paged in
        the same window — ``observability/fleet.py``)."""
        return {}

    def _journal_tail(self) -> Optional[str]:
        if not self.journal_path or not os.path.exists(self.journal_path):
            return None
        tail: Deque[str] = collections.deque(maxlen=_JOURNAL_TAIL_LINES)
        with open(self.journal_path) as f:
            for line in f:
                if line.endswith("\n"):   # a torn in-flight append is
                    tail.append(line)     # dropped, the loader convention
        return "".join(tail)

    def _chrome_dump(self, tail: Optional[str]) -> Optional[dict]:
        """Flight-recorder Chrome trace of the CURRENT ring, when a tracer
        is active (``Tracer.snapshot_chrome`` — the dump hook).  The journal
        events annotated onto the trace come from the already-read ``tail``
        window — the journal file is read ONCE per bundle and the parse is
        bounded by the same 256-line cap, so a paging tick on a service with
        hours of journal never stalls re-reading the whole file.  Lazy
        relative import: under the stdlib CLIs' synthetic package tracing is
        never loaded, and capture is never invoked there."""
        try:
            from . import tracing as _tracing
        except ImportError:
            return None
        tr = _tracing.get_active()
        if tr is None:
            return None
        try:
            jevents = None
            if tail:
                jevents = []
                for line in tail.splitlines():
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        jevents.append(json.loads(line))
                    except ValueError:
                        continue
            return tr.snapshot_chrome(journal_events=jevents)
        except Exception:   # noqa: BLE001 — forensics must never kill the
            return None     # reporter tick; the bundle just omits the trace

    def _config_fingerprint(self) -> dict:
        out = {"env": {k: v for k, v in sorted(os.environ.items())
                       if k.startswith("WF_")}}
        if self.fingerprint is not None:
            try:
                extra = self.fingerprint()
            except Exception:   # noqa: BLE001 — a half-built registry must
                extra = None    # not kill the capture; env still lands
            if extra:
                out.update(extra)
        return out


# ------------------------------------------------------ offline evaluation


def evaluate_series(specs: Sequence[SLOSpec],
                    series: Sequence[dict]) -> Dict[str, dict]:
    """Run the burn/state machine over a snapshot time series (the
    ``snapshots.jsonl`` semantics) without journaling or capturing —
    ``scripts/wf_slo.py``'s engine.  Input snapshots are not mutated."""
    eng = SLOEngine(specs, out_dir=None, journal=False)
    for snap in series:
        eng.observe(dict(snap))
    return eng.report()


def burning(report: Dict[str, dict]) -> List[str]:
    """Names of the SLOs whose FINAL state is not OK — the wf_slo.py
    exit-1 condition."""
    return sorted(n for n, row in report.items() if row.get("burning"))


# ------------------------------------------------------------ bundle reads


def list_incidents(mon_dir: str) -> Tuple[List[dict], List[str]]:
    """(committed bundles newest-last, torn directory names) under
    ``<mon_dir>/incidents``.  A bundle is its manifest plus ``path`` and a
    ``missing`` list of manifest-declared files that are absent/empty — the
    validation surface of ``wf_slo.py --json`` and the ``incidents``
    sections of ``wf_health.py``/``wf_state.py``."""
    root = os.path.join(mon_dir, "incidents")
    bundles, torn = [], []
    if not os.path.isdir(root):
        return bundles, torn
    for entry in sorted(os.listdir(root)):
        d = os.path.join(root, entry)
        if not os.path.isdir(d):
            continue
        mpath = os.path.join(d, "manifest.json")
        try:
            with open(mpath) as f:
                man = json.load(f)
        except (OSError, ValueError):
            torn.append(entry)            # crash mid-capture: manifest is
            continue                      # the commit point it never reached
        man = dict(man)
        man["path"] = d
        missing = []
        for fname in man.get("files", []):
            p = os.path.join(d, fname)
            if not os.path.exists(p) or os.path.getsize(p) == 0:
                missing.append(fname)
        man["missing"] = missing
        bundles.append(man)
    bundles.sort(key=lambda m: m.get("wall", 0.0))
    return bundles, torn


def incidents_summary(mon_dir: str) -> dict:
    """Compact cross-reference for the sibling CLIs: bundle count, torn
    count, and the newest bundle's path + triggering SLO."""
    bundles, torn = list_incidents(mon_dir)
    out: dict = {"count": len(bundles), "torn": len(torn)}
    if bundles:
        last = bundles[-1]
        out["last"] = {"path": last["path"], "slo": last.get("slo"),
                       "wall": last.get("wall"),
                       "state": last.get("state")}
    return out
