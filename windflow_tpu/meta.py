"""Signature introspection for user functions.

The reference deduces ``tuple_t``/``result_t`` and the function *flavour* (plain/rich,
in-place/non-in-place, itemized/loop) from ``&F_t::operator()`` by template
metaprogramming (``wf/meta.hpp:49-877``, ``wf/meta_gpu.hpp``, catalogue in
``/root/reference/API``). The Python counterpart inspects ``inspect.signature`` to
classify the callable once at operator-construction time, so builders can reject
ill-formed functions *at graph-build time* with an explicit list of accepted
signatures — mirroring the reference's static_assert messages
(``wf/builders.hpp:56-58``).

Accepted signatures (per-tuple functions run under ``vmap``; ``t`` is a
:class:`~windflow_tpu.batch.TupleRef`):

- Source   : ``f(i, ctx?) -> payload``            (itemized; ``i`` = global index array)
- Map      : ``f(t, ctx?) -> payload``            (non-in-place; key/id/ts preserved)
- Filter   : ``f(t, ctx?) -> bool``
- FlatMap  : ``f(t, shipper, ctx?) -> None``      (push-style, static max fan-out)
- Accumulator: ``f(acc, t, ctx?) -> acc``
- Window (non-incremental): ``f(wid, iterable, ctx?) -> result``
- Window (incremental)    : ``f(wid, t, acc, ctx?) -> acc``
- Combine (associative)   : ``f(a, b) -> c``
- Sink     : ``f(payload_dict_of_numpy, ctx?) -> None``  (host-side, per live batch)
"""

from __future__ import annotations

import inspect
import warnings
from typing import Callable

RICH_PARAM_NAMES = ("ctx", "context", "rc")


class FlavourWarning(UserWarning):
    """A flavour was deduced from a parameter NAME that is not in the
    recognized list — the deduction proceeds (documented behavior, docs/API.md)
    but the name suggests the user may have meant the other flavour."""


def _warn_flavour(msg: str) -> None:
    warnings.warn(msg, FlavourWarning, stacklevel=4)


class SignatureError(TypeError):
    """Raised at graph-build time when a user callable has an unusable signature."""


def _positional_params(fn: Callable):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    return [p for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]


def classify(fn: Callable, *, base_arity: int, what: str, accepted: str):
    """Return ``is_rich`` for a user callable expected to take ``base_arity``
    positional args, optionally followed by a RuntimeContext parameter.

    Counterpart of the per-operator ``get_tuple_t_X`` overload families
    (``wf/meta.hpp:49-88`` for Source, etc.)."""
    params = _positional_params(fn)
    if params is None:
        # builtins / jitted callables without signatures: assume plain
        return False
    n = len(params)
    if n == base_arity:
        return False
    if n == base_arity + 1:
        if params[-1].name not in RICH_PARAM_NAMES:
            _warn_flavour(
                f"{what}: trailing parameter {params[-1].name!r} is treated as "
                f"the RuntimeContext (rich flavour); name it one of "
                f"{RICH_PARAM_NAMES} to silence this warning")
        return True
    raise SignatureError(
        f"{what}: callable takes {n} positional parameters; accepted signatures are:\n"
        f"  {accepted}\n"
        f"(append a trailing context parameter named one of {RICH_PARAM_NAMES} for the"
        f" rich variant — wf/meta.hpp semantics)")


#: parameter names marking a Shipper parameter (loop-style Source flavour)
SHIPPER_PARAM_NAMES = ("shipper", "ship", "out", "emit")

SOURCE_CATALOGUE = """\
  f(i) -> payload                       (itemized; bool(tuple_t&) analogue)
  f(i, ctx) -> payload                  (itemized rich)
  f(i, shipper) -> None                 (loop; bool(Shipper<tuple_t>&) analogue)
  f(i, shipper, ctx) -> None            (loop rich)
(catalogue: /root/reference/API SOURCE; the shipper parameter must be named one
of %s, the context parameter one of %s)""" % (SHIPPER_PARAM_NAMES,
                                              RICH_PARAM_NAMES)

WINDOW_CATALOGUE = """\
  f(wid, iterable) -> result            (non-incremental)
  f(wid, iterable, ctx) -> result       (non-incremental rich)
  f(wid, t, acc) -> acc                 (incremental; winupdate)
  f(wid, t, acc, ctx) -> acc            (incremental rich)
(catalogue: /root/reference/API KEY_FARM/WIN_FARM; the context parameter must be
named one of %s)""" % (RICH_PARAM_NAMES,)


def classify_source(fn):
    return classify(fn, base_arity=1, what="Source",
                    accepted="f(i) -> payload | f(i, ctx) -> payload")


def classify_source_flavour(fn):
    """Deduce the Source flavour: ``(loop, is_rich)``.

    The reference accepts itemized ``bool(tuple_t&)`` and loop ``bool(Shipper&)``
    sources (+rich; ``wf/meta.hpp:49-88``, ``/root/reference/API``). Here the
    itemized form is ``f(i) -> payload`` and the loop form ``f(i, shipper)`` —
    the shipper records 0..max_fanout pushes per index (``when=`` masks make
    emission data-dependent)."""
    params = _positional_params(fn)
    if params is None:
        return False, False
    names = [p.name for p in params]
    n = len(names)
    if n == 1:
        return False, False
    if n == 2:
        # a shipper-named 2nd param selects the loop flavour; any other name is
        # treated as the context (the itemized rich form — arity compatibility
        # with plain classify_source)
        if names[1] in SHIPPER_PARAM_NAMES:
            return True, False
        if names[1] not in RICH_PARAM_NAMES:
            _warn_flavour(
                f"Source: parameter {names[1]!r} is treated as the "
                f"RuntimeContext (itemized rich flavour); for a LOOP source "
                f"name it one of {SHIPPER_PARAM_NAMES}, for a context one of "
                f"{RICH_PARAM_NAMES}")
        return False, True
    if n == 3 and names[1] in SHIPPER_PARAM_NAMES:
        return True, True
    raise SignatureError(
        f"Source: callable with positional parameters {names} matches no accepted "
        f"signature:\n{SOURCE_CATALOGUE}")


def classify_window_flavour(fn):
    """Deduce the window-function flavour: ``(incremental, is_rich)``.

    The reference dispatches non-incremental ``void(wid, Iterable&, result&)`` vs
    incremental ``void(wid, tuple&, result&)`` statically (``wf/meta.hpp`` window
    families); here arity separates them (2 vs 3 args) with the trailing
    context-named parameter marking rich forms."""
    params = _positional_params(fn)
    if params is None:
        return False, False
    names = [p.name for p in params]
    n = len(names)
    if n == 2:
        return False, False
    if n == 3:
        if names[-1] in RICH_PARAM_NAMES:
            return False, True
        if any(m in names[-1].lower() for m in ("ctx", "context")):
            _warn_flavour(
                f"Window function: parameter {names[-1]!r} looks like a "
                f"context but is not named one of {RICH_PARAM_NAMES}, so the "
                f"INCREMENTAL flavour (f(wid, t, acc)) was deduced; rename it "
                f"if you meant the non-incremental rich form")
        return True, False
    if n == 4 and names[-1] in RICH_PARAM_NAMES:
        return True, True
    raise SignatureError(
        f"Window function: callable with positional parameters {names} matches no "
        f"accepted signature:\n{WINDOW_CATALOGUE}")


def classify_map(fn):
    return classify(fn, base_arity=1, what="Map",
                    accepted="f(t) -> payload | f(t, ctx) -> payload")


def classify_filter(fn):
    return classify(fn, base_arity=1, what="Filter",
                    accepted="f(t) -> bool | f(t, ctx) -> bool")


def classify_flatmap(fn):
    return classify(fn, base_arity=2, what="FlatMap",
                    accepted="f(t, shipper) | f(t, shipper, ctx)")


def classify_accumulator(fn):
    return classify(fn, base_arity=2, what="Accumulator",
                    accepted="f(acc, t) -> acc | f(acc, t, ctx) -> acc")


def classify_window(fn):
    return classify(fn, base_arity=2, what="Window function",
                    accepted="f(wid, iterable) -> result | f(wid, iterable, ctx) -> result")


def classify_winupdate(fn):
    return classify(fn, base_arity=3, what="Incremental window function",
                    accepted="f(wid, t, acc) -> acc | f(wid, t, acc, ctx) -> acc")


def classify_sink(fn):
    return classify(fn, base_arity=1, what="Sink",
                    accepted="f(batch) | f(batch, ctx)")
