"""Signature introspection for user functions.

The reference deduces ``tuple_t``/``result_t`` and the function *flavour* (plain/rich,
in-place/non-in-place, itemized/loop) from ``&F_t::operator()`` by template
metaprogramming (``wf/meta.hpp:49-877``, ``wf/meta_gpu.hpp``, catalogue in
``/root/reference/API``). The Python counterpart inspects ``inspect.signature`` to
classify the callable once at operator-construction time, so builders can reject
ill-formed functions *at graph-build time* with an explicit list of accepted
signatures — mirroring the reference's static_assert messages
(``wf/builders.hpp:56-58``).

Accepted signatures (per-tuple functions run under ``vmap``; ``t`` is a
:class:`~windflow_tpu.batch.TupleRef`):

- Source   : ``f(i, ctx?) -> payload``            (itemized; ``i`` = global index array)
- Map      : ``f(t, ctx?) -> payload``            (non-in-place; key/id/ts preserved)
- Filter   : ``f(t, ctx?) -> bool``
- FlatMap  : ``f(t, shipper, ctx?) -> None``      (push-style, static max fan-out)
- Accumulator: ``f(acc, t, ctx?) -> acc``
- Window (non-incremental): ``f(wid, iterable, ctx?) -> result``
- Window (incremental)    : ``f(wid, t, acc, ctx?) -> acc``
- Combine (associative)   : ``f(a, b) -> c``
- Sink     : ``f(payload_dict_of_numpy, ctx?) -> None``  (host-side, per live batch)
"""

from __future__ import annotations

import inspect
from typing import Callable

RICH_PARAM_NAMES = ("ctx", "context", "rc")


class SignatureError(TypeError):
    """Raised at graph-build time when a user callable has an unusable signature."""


def _positional_params(fn: Callable):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    return [p for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]


def classify(fn: Callable, *, base_arity: int, what: str, accepted: str):
    """Return ``is_rich`` for a user callable expected to take ``base_arity``
    positional args, optionally followed by a RuntimeContext parameter.

    Counterpart of the per-operator ``get_tuple_t_X`` overload families
    (``wf/meta.hpp:49-88`` for Source, etc.)."""
    params = _positional_params(fn)
    if params is None:
        # builtins / jitted callables without signatures: assume plain
        return False
    n = len(params)
    if n == base_arity:
        return False
    if n == base_arity + 1:
        return True
    raise SignatureError(
        f"{what}: callable takes {n} positional parameters; accepted signatures are:\n"
        f"  {accepted}\n"
        f"(append a trailing context parameter named one of {RICH_PARAM_NAMES} for the"
        f" rich variant — wf/meta.hpp semantics)")


def classify_source(fn):
    return classify(fn, base_arity=1, what="Source",
                    accepted="f(i) -> payload | f(i, ctx) -> payload")


def classify_map(fn):
    return classify(fn, base_arity=1, what="Map",
                    accepted="f(t) -> payload | f(t, ctx) -> payload")


def classify_filter(fn):
    return classify(fn, base_arity=1, what="Filter",
                    accepted="f(t) -> bool | f(t, ctx) -> bool")


def classify_flatmap(fn):
    return classify(fn, base_arity=2, what="FlatMap",
                    accepted="f(t, shipper) | f(t, shipper, ctx)")


def classify_accumulator(fn):
    return classify(fn, base_arity=2, what="Accumulator",
                    accepted="f(acc, t) -> acc | f(acc, t, ctx) -> acc")


def classify_window(fn):
    return classify(fn, base_arity=2, what="Window function",
                    accepted="f(wid, iterable) -> result | f(wid, iterable, ctx) -> result")


def classify_winupdate(fn):
    return classify(fn, base_arity=3, what="Incremental window function",
                    accepted="f(wid, t, acc) -> acc | f(wid, t, acc, ctx) -> acc")


def classify_sink(fn):
    return classify(fn, base_arity=1, what="Sink",
                    accepted="f(batch) | f(batch, ctx)")
