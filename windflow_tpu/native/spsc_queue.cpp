// Native host runtime substrate: lock-free SPSC ring queues + thread pinning.
//
// This is the FastFlow role in the reference (L0: ff_node threads connected by
// lock-free SPSC queues, SURVEY §1; wf/windflow.hpp includes <ff/ff.hpp>), rebuilt
// for the TPU host: operator stages exchange *micro-batch handles* (opaque 64-bit
// tokens naming device buffers) through bounded SPSC rings, giving the same
// backpressure semantics as the reference's FF_BOUNDED_BUFFER queues. The device
// work itself is dispatched by the stage that owns the batch; the queue only moves
// handles, so the native layer is allocation-free and wait-free on the fast path.
//
// C ABI for ctypes binding (pybind11 is not available in this image).
//
// Design notes (mirroring FastFlow's buffer):
//  - capacity rounded to a power of two; index wrap via mask
//  - head/tail on separate cache lines to avoid false sharing
//  - push/pop are wait-free; *_spin variants bound the spin then yield
//    (BLOCKING_MODE-equivalent behavior)

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <thread>

#if defined(__linux__)
#include <pthread.h>
#include <sched.h>
#endif

namespace {

constexpr size_t kCacheLine = 64;

struct alignas(kCacheLine) SpscQueue {
    uint64_t* buf;
    uint64_t mask;
    alignas(kCacheLine) std::atomic<uint64_t> head;  // consumer position
    alignas(kCacheLine) std::atomic<uint64_t> tail;  // producer position

    explicit SpscQueue(uint64_t capacity_pow2)
        : buf(static_cast<uint64_t*>(std::calloc(capacity_pow2, sizeof(uint64_t)))),
          mask(capacity_pow2 - 1), head(0), tail(0) {}
    ~SpscQueue() { std::free(buf); }
};

uint64_t next_pow2(uint64_t n) {
    uint64_t p = 1;
    while (p < n) p <<= 1;
    return p;
}

}  // namespace

extern "C" {

void* wf_queue_create(uint64_t capacity) {
    return new SpscQueue(next_pow2(capacity < 2 ? 2 : capacity));
}

void wf_queue_destroy(void* q) { delete static_cast<SpscQueue*>(q); }

// Wait-free push; returns 0 when the ring is full (bounded backpressure,
// FF_BOUNDED_BUFFER semantics).
int wf_queue_push(void* qp, uint64_t item) {
    auto* q = static_cast<SpscQueue*>(qp);
    const uint64_t t = q->tail.load(std::memory_order_relaxed);
    if (t - q->head.load(std::memory_order_acquire) > q->mask) return 0;
    q->buf[t & q->mask] = item;
    q->tail.store(t + 1, std::memory_order_release);
    return 1;
}

// Wait-free pop; returns 0 when empty (item untouched).
int wf_queue_pop(void* qp, uint64_t* item) {
    auto* q = static_cast<SpscQueue*>(qp);
    const uint64_t h = q->head.load(std::memory_order_relaxed);
    if (h == q->tail.load(std::memory_order_acquire)) return 0;
    *item = q->buf[h & q->mask];
    q->head.store(h + 1, std::memory_order_release);
    return 1;
}

// Spinning variants: spin `spin` times, then yield between retries until success
// (push) or until `max_yields` yields have elapsed (pop; returns 0 on timeout so
// callers can check shutdown flags). GIL is released by ctypes for the duration.
int wf_queue_push_spin(void* qp, uint64_t item, uint64_t spin) {
    for (;;) {
        for (uint64_t i = 0; i < spin; ++i)
            if (wf_queue_push(qp, item)) return 1;
        std::this_thread::yield();
    }
}

int wf_queue_pop_spin(void* qp, uint64_t* item, uint64_t spin, uint64_t max_yields) {
    for (uint64_t y = 0; y <= max_yields; ++y) {
        for (uint64_t i = 0; i < spin; ++i)
            if (wf_queue_pop(qp, item)) return 1;
        std::this_thread::yield();
    }
    return 0;
}

uint64_t wf_queue_size(void* qp) {
    auto* q = static_cast<SpscQueue*>(qp);
    return q->tail.load(std::memory_order_acquire) -
           q->head.load(std::memory_order_acquire);
}

// Pin the calling thread to a core (the reference pins one thread per ff_node
// unless NO_DEFAULT_MAPPING). Returns 0 on success.
int wf_pin_thread(int core) {
#if defined(__linux__)
    cpu_set_t set;
    CPU_ZERO(&set);
    CPU_SET(core, &set);
    return pthread_setaffinity_np(pthread_self(), sizeof(set), &set);
#else
    (void)core;
    return -1;
#endif
}

int wf_hardware_concurrency() {
    return static_cast<int>(std::thread::hardware_concurrency());
}

// Self-benchmark of the raw ring (no Python in the loop): producer and
// consumer threads on cores 0/1 move n tokens; returns tokens/second. This is
// the number FastFlow's lock-free queues compete on (reference L0).
double wf_queue_selfbench(uint64_t n, uint64_t capacity) {
    void* q = wf_queue_create(capacity);
    // short spins: on a single-core host long spin loops burn whole scheduler
    // quanta against the peer thread; on multi-core the difference is noise
    bool multi = std::thread::hardware_concurrency() >= 2;
    uint64_t spin = multi ? (1 << 12) : 64;
    auto t0 = std::chrono::steady_clock::now();
    std::thread prod([&] {
        if (multi) wf_pin_thread(0);
        for (uint64_t i = 1; i <= n; ++i) wf_queue_push_spin(q, i, spin);
    });
    uint64_t sum = 0;
    std::thread cons([&] {
        if (multi) wf_pin_thread(1);
        uint64_t got = 0, v = 0;
        while (got < n) {
            if (wf_queue_pop_spin(q, &v, spin, 1)) { sum += v; ++got; }
        }
    });
    prod.join();
    cons.join();
    auto dt = std::chrono::duration<double>(
        std::chrono::steady_clock::now() - t0).count();
    wf_queue_destroy(q);
    // defeat dead-code elimination of the consumer sum
    if (sum == 0 && n > 0) return -1.0;
    return static_cast<double>(n) / dt;
}

}  // extern "C"
