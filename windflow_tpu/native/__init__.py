"""ctypes binding for the native host runtime (SPSC queues + thread pinning).

Builds ``libwfnative.so`` from ``spsc_queue.cpp`` on first import if missing (g++ is
part of the toolchain); falls back to a pure-Python deque shim when no compiler is
available so the threaded scheduler still works (correctness first, the native ring is
the fast path)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections import deque

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libwfnative.so")

_lib = None


_load_failed = False            # sticky: a failed build/load is not retried per call


def _build():
    """Compile to a temp name and rename over the target only on success — a stale
    but working .so is never destroyed by a failed rebuild."""
    tmp = _SO + ".tmp"
    try:
        subprocess.run(["make", "-C", _DIR, f"TARGET={os.path.basename(tmp)}"],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None

    def fail():
        global _load_failed
        _load_failed = True
        return None

    if not os.path.exists(_SO) and not _build():
        return fail()
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return fail()
    if not _bind(lib):
        # stale .so: it predates some symbol in _SYMBOLS (the library is
        # gitignored and survives pulls) — rebuild once, else fall back to the
        # pure-Python shims. Staleness is derived from the SAME table the
        # binding uses, so it cannot drift from the binding code.
        del lib
        if not _build():
            return fail()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return fail()
        if not _bind(lib):
            return fail()
    _lib = lib
    return lib


_P = ctypes.POINTER
#: every exported symbol with its signature — the single source of truth for
#: both binding and stale-.so detection (None restype = ctypes default c_int)
_SYMBOLS = [
    ("wf_queue_create", ctypes.c_void_p, [ctypes.c_uint64]),
    ("wf_queue_destroy", None, [ctypes.c_void_p]),
    ("wf_queue_push", ctypes.c_int, [ctypes.c_void_p, ctypes.c_uint64]),
    ("wf_queue_pop", ctypes.c_int, [ctypes.c_void_p, _P(ctypes.c_uint64)]),
    ("wf_queue_push_spin", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]),
    ("wf_queue_pop_spin", ctypes.c_int,
     [ctypes.c_void_p, _P(ctypes.c_uint64), ctypes.c_uint64, ctypes.c_uint64]),
    ("wf_queue_size", ctypes.c_uint64, [ctypes.c_void_p]),
    ("wf_pin_thread", ctypes.c_int, [ctypes.c_int]),
    ("wf_hardware_concurrency", ctypes.c_int, []),
    ("wf_queue_selfbench", ctypes.c_double, [ctypes.c_uint64, ctypes.c_uint64]),
    ("wf_unpack_records", None,
     [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
      _P(ctypes.c_uint64), _P(ctypes.c_uint64), _P(ctypes.c_char_p)]),
    ("wf_pack_records", None,
     [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
      _P(ctypes.c_uint64), _P(ctypes.c_uint64), _P(ctypes.c_char_p)]),
    ("wf_hash_str_keys", None,
     [ctypes.c_char_p, _P(ctypes.c_int64), ctypes.c_uint64, ctypes.c_uint32,
      _P(ctypes.c_int32)]),
    ("wf_hash_fixed_str_keys", None,
     [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
      ctypes.c_uint32, _P(ctypes.c_int32)]),
    ("wf_hash_int_keys", None,
     [_P(ctypes.c_int64), ctypes.c_uint64, ctypes.c_uint32, _P(ctypes.c_int32)]),
]


def _bind(lib) -> bool:
    """Bind every symbol in ``_SYMBOLS``; False if any is missing (stale .so)."""
    for name, restype, argtypes in _SYMBOLS:
        if not hasattr(lib, name):
            return False
        fn = getattr(lib, name)
        if restype is not None:
            fn.restype = restype
        fn.argtypes = argtypes
    return True


class SPSCQueue:
    """Bounded SPSC queue of Python objects backed by the native ring: the ring moves
    opaque uint64 tokens; a side table maps tokens to objects (batch handles). The
    token table is written only by the producer and cleared only by the consumer —
    the SPSC discipline keeps it race-free without locks."""

    def __init__(self, capacity: int = 1024):
        lib = _load()
        self._lib = lib
        self._objs = {}
        self._seq = 0
        if lib is not None:
            self._q = lib.wf_queue_create(capacity)
        else:                               # pure-Python fallback
            self._q = None
            self._dq = deque()
            self._cap = capacity
            self._cv = threading.Condition()

    def push(self, obj, spin: int = 1024) -> None:
        if self._q is not None:
            self._seq += 1
            tok = self._seq
            self._objs[tok] = obj
            self._lib.wf_queue_push_spin(self._q, tok, spin)
        else:
            with self._cv:
                while len(self._dq) >= self._cap:
                    self._cv.wait(0.001)
                self._dq.append(obj)
                self._cv.notify_all()

    def pop(self, spin: int = 1024, max_yields: int = 1 << 20):
        """Returns (ok, obj)."""
        if self._q is not None:
            tok = ctypes.c_uint64()
            ok = self._lib.wf_queue_pop_spin(self._q, ctypes.byref(tok),
                                             spin, max_yields)
            if not ok:
                return False, None
            return True, self._objs.pop(tok.value)
        with self._cv:
            while not self._dq:
                if not self._cv.wait(1.0):
                    return False, None
            obj = self._dq.popleft()
            self._cv.notify_all()
            return True, obj

    def size(self) -> int:
        if self._q is not None:
            return int(self._lib.wf_queue_size(self._q))
        return len(self._dq)

    def __del__(self):
        if getattr(self, "_q", None) is not None and self._lib is not None:
            self._lib.wf_queue_destroy(self._q)
            self._q = None


def unpack_records(records, fields=None):
    """AoS -> SoA in one native pass: ``records`` is a numpy structured array
    (the framing of network/disk ingest); returns ``{field: contiguous column}``.
    The native counterpart of the reference's per-tuple Source/Shipper copy path
    (``wf/source.hpp:184``, ``wf/shipper.hpp:87``). Falls back to numpy per-field
    copies when the native library is unavailable."""
    import numpy as np
    lib = _load()
    dt = records.dtype
    names = list(fields if fields is not None else dt.names)
    if lib is None or not records.flags["C_CONTIGUOUS"]:
        return {f: np.ascontiguousarray(records[f]) for f in names}
    n = records.shape[0]
    outs, dsts, offs, szs = {}, [], [], []
    for f in names:
        fdt, off = dt.fields[f][0], dt.fields[f][1]
        col = np.empty(n, fdt)
        outs[f] = col
        dsts.append(col.ctypes.data_as(ctypes.c_char_p))
        offs.append(off)
        szs.append(fdt.itemsize)
    nf = len(names)
    lib.wf_unpack_records(
        records.ctypes.data_as(ctypes.c_char_p), n, dt.itemsize, nf,
        (ctypes.c_uint64 * nf)(*offs), (ctypes.c_uint64 * nf)(*szs),
        (ctypes.c_char_p * nf)(*dsts))
    # structured subdtypes (e.g. ('f4', (3,))) come back flat; reshape
    for f in names:
        sub = dt.fields[f][0]
        if sub.subdtype is not None:
            outs[f] = outs[f].view(sub.subdtype[0]).reshape((n,) + sub.subdtype[1])
    return outs


def parallel_unpack(records, workers: int = None, fields=None):
    """Sharded AoS -> SoA framing: the record buffer is split into ``workers``
    contiguous row slices, each transposed by :func:`unpack_records`'s native
    pass in its own thread, writing DIRECTLY into the shared preallocated
    columns at its row offset (no per-slice allocation, no concat, order
    trivially preserved). ctypes releases the GIL around each native call, so
    slices unpack truly concurrently — the counterpart of the reference
    sweeping 1-14 source threads (``src/GPU_Tests/new_tests/run_tests.py:20-28``,
    replica splitting ``wf/source.hpp:284-296``) applied to host framing.

    ``workers=None`` uses ``hardware_concurrency()``; 1 (or a single-core host,
    or no native library) degrades to the plain single-pass path."""
    import numpy as np
    lib = _load()
    if workers is None:
        workers = hardware_concurrency()
    n = records.shape[0]
    workers = max(1, min(int(workers), n or 1))
    if (lib is None or workers == 1 or not records.flags["C_CONTIGUOUS"]):
        return unpack_records(records, fields)
    import threading
    dt = records.dtype
    names = list(fields if fields is not None else dt.names)
    outs = {f: np.empty(n, dt.fields[f][0]) for f in names}
    bounds = [round(w * n / workers) for w in range(workers + 1)]
    rec_base = records.ctypes.data
    nf = len(names)
    offs = (ctypes.c_uint64 * nf)(*[dt.fields[f][1] for f in names])
    szs = (ctypes.c_uint64 * nf)(*[dt.fields[f][0].itemsize for f in names])

    as_cp = lambda addr: ctypes.cast(ctypes.c_void_p(addr), ctypes.c_char_p)

    def one(lo, hi):
        m = hi - lo
        if m <= 0:
            return
        dsts = (ctypes.c_char_p * nf)(*[
            # per-ROW stride is the FIELD dtype's itemsize (12 for ('f4',(3,));
            # the allocated array's base dtype would say 4)
            as_cp(outs[f].ctypes.data + lo * dt.fields[f][0].itemsize)
            for f in names])
        lib.wf_unpack_records(
            as_cp(rec_base + lo * dt.itemsize), m, dt.itemsize, nf,
            offs, szs, dsts)

    threads = [threading.Thread(target=one,  # wf-lint: thread-role[native]
                                args=(bounds[w], bounds[w + 1]))
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for f in names:                       # structured subdtypes come back flat
        sub = dt.fields[f][0]
        if sub.subdtype is not None:
            outs[f] = outs[f].view(sub.subdtype[0]).reshape((n,) + sub.subdtype[1])
    return outs


def pack_records(columns: dict, dtype):
    """SoA -> AoS egress (sinks emitting framed records): inverse of
    :func:`unpack_records`."""
    import numpy as np
    lib = _load()
    names = list(dtype.names)
    n = len(np.asarray(columns[names[0]]))
    out = np.empty(n, dtype)
    # validate every column against its field BEFORE any copy, native or not —
    # same error either way, and no native out-of-bounds read
    cols = []
    for f in names:
        fdt = dtype.fields[f][0]
        col = np.ascontiguousarray(np.asarray(columns[f]),
                                   fdt.base if fdt.subdtype else fdt)
        if col.nbytes != n * fdt.itemsize:
            raise ValueError(
                f"pack_records: column '{f}' has {col.shape} {col.dtype} "
                f"({col.nbytes} bytes) but field needs {n} x {fdt.itemsize} bytes")
        cols.append(col)                         # also keeps ctypes pointers alive
    if lib is None:
        for f, col in zip(names, cols):
            sub = dtype.fields[f][0].subdtype
            out[f] = col.reshape((n,) + sub[1]) if sub else col
        return out
    srcs, offs, szs = [], [], []
    for f, col in zip(names, cols):
        fdt, off = dtype.fields[f][0], dtype.fields[f][1]
        srcs.append(col.ctypes.data_as(ctypes.c_char_p))
        offs.append(off)
        szs.append(fdt.itemsize)
    nf = len(names)
    lib.wf_pack_records(
        out.ctypes.data_as(ctypes.c_char_p), n, dtype.itemsize, nf,
        (ctypes.c_uint64 * nf)(*offs), (ctypes.c_uint64 * nf)(*szs),
        (ctypes.c_char_p * nf)(*srcs))
    return out


def hash_keys_native(keys, num_slots: int):
    """Native key->slot hashing, bit-identical to
    ``windflow_tpu.batch.hash_key_to_slot``: 32-bit FNV-1a for string/bytes arrays,
    Knuth uint64 multiply for integer arrays. Returns int32 slots, or None when the
    native library is unavailable (caller falls back to the Python path)."""
    import numpy as np
    lib = _load()
    if lib is None:
        return None
    arr = np.asarray(keys)
    out = np.empty(arr.size, np.int32)
    if arr.dtype.kind in "iu":
        k = np.ascontiguousarray(arr.ravel().astype(np.int64))
        lib.wf_hash_int_keys(k.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                             arr.size, num_slots,
                             out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out.reshape(arr.shape)
    if arr.dtype.kind == "S":
        a = np.ascontiguousarray(arr.ravel())
        lib.wf_hash_fixed_str_keys(
            a.ctypes.data_as(ctypes.c_char_p), a.size, a.dtype.itemsize,
            a.dtype.itemsize, num_slots,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out.reshape(arr.shape)
    if arr.dtype.kind == "U":
        # dedup first (batches typically repeat few keys), hash uniques natively,
        # scatter back through the inverse index
        uniq, inv = np.unique(arr.ravel(), return_inverse=True)
        enc = [s.encode() for s in uniq.tolist()]
        buf = b"".join(enc)
        offsets = np.zeros(len(enc) + 1, np.int64)
        np.cumsum([len(e) for e in enc], out=offsets[1:])
        uout = np.empty(len(enc), np.int32)
        lib.wf_hash_str_keys(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(enc), num_slots,
            uout.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return uout[inv].reshape(arr.shape)
    return None


def pin_thread(core: int) -> bool:
    lib = _load()
    return lib is not None and lib.wf_pin_thread(core) == 0


def hardware_concurrency() -> int:
    lib = _load()
    return lib.wf_hardware_concurrency() if lib is not None else (os.cpu_count() or 1)


def native_available() -> bool:
    return _load() is not None


def queue_selfbench(n: int = 2_000_000, capacity: int = 1024) -> float:
    """Raw ring throughput (tokens/s), measured entirely in C across two
    threads (``wf_queue_selfbench``) — the number the reference's FastFlow
    SPSC queues compete on. Returns 0.0 without the native library."""
    lib = _load()
    if lib is None:
        return 0.0
    return float(lib.wf_queue_selfbench(n, capacity))
