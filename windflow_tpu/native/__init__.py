"""ctypes binding for the native host runtime (SPSC queues + thread pinning).

Builds ``libwfnative.so`` from ``spsc_queue.cpp`` on first import if missing (g++ is
part of the toolchain); falls back to a pure-Python deque shim when no compiler is
available so the threaded scheduler still works (correctness first, the native ring is
the fast path)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections import deque

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libwfnative.so")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO):
        try:
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.wf_queue_create.restype = ctypes.c_void_p
    lib.wf_queue_create.argtypes = [ctypes.c_uint64]
    lib.wf_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.wf_queue_push.restype = ctypes.c_int
    lib.wf_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.wf_queue_pop.restype = ctypes.c_int
    lib.wf_queue_pop.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.wf_queue_push_spin.restype = ctypes.c_int
    lib.wf_queue_push_spin.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_uint64]
    lib.wf_queue_pop_spin.restype = ctypes.c_int
    lib.wf_queue_pop_spin.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.c_uint64, ctypes.c_uint64]
    lib.wf_queue_size.restype = ctypes.c_uint64
    lib.wf_queue_size.argtypes = [ctypes.c_void_p]
    lib.wf_pin_thread.restype = ctypes.c_int
    lib.wf_pin_thread.argtypes = [ctypes.c_int]
    lib.wf_hardware_concurrency.restype = ctypes.c_int
    _lib = lib
    return lib


class SPSCQueue:
    """Bounded SPSC queue of Python objects backed by the native ring: the ring moves
    opaque uint64 tokens; a side table maps tokens to objects (batch handles). The
    token table is written only by the producer and cleared only by the consumer —
    the SPSC discipline keeps it race-free without locks."""

    def __init__(self, capacity: int = 1024):
        lib = _load()
        self._lib = lib
        self._objs = {}
        self._seq = 0
        if lib is not None:
            self._q = lib.wf_queue_create(capacity)
        else:                               # pure-Python fallback
            self._q = None
            self._dq = deque()
            self._cap = capacity
            self._cv = threading.Condition()

    def push(self, obj, spin: int = 1024) -> None:
        if self._q is not None:
            self._seq += 1
            tok = self._seq
            self._objs[tok] = obj
            self._lib.wf_queue_push_spin(self._q, tok, spin)
        else:
            with self._cv:
                while len(self._dq) >= self._cap:
                    self._cv.wait(0.001)
                self._dq.append(obj)
                self._cv.notify_all()

    def pop(self, spin: int = 1024, max_yields: int = 1 << 20):
        """Returns (ok, obj)."""
        if self._q is not None:
            tok = ctypes.c_uint64()
            ok = self._lib.wf_queue_pop_spin(self._q, ctypes.byref(tok),
                                             spin, max_yields)
            if not ok:
                return False, None
            return True, self._objs.pop(tok.value)
        with self._cv:
            while not self._dq:
                if not self._cv.wait(1.0):
                    return False, None
            obj = self._dq.popleft()
            self._cv.notify_all()
            return True, obj

    def size(self) -> int:
        if self._q is not None:
            return int(self._lib.wf_queue_size(self._q))
        return len(self._dq)

    def __del__(self):
        if getattr(self, "_q", None) is not None and self._lib is not None:
            self._lib.wf_queue_destroy(self._q)
            self._q = None


def pin_thread(core: int) -> bool:
    lib = _load()
    return lib is not None and lib.wf_pin_thread(core) == 0


def hardware_concurrency() -> int:
    lib = _load()
    return lib.wf_hardware_concurrency() if lib is not None else (os.cpu_count() or 1)


def native_available() -> bool:
    return _load() is not None
