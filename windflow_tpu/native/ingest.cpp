// Native host ingest/egress: AoS record <-> SoA column packing and key hashing.
//
// Reference lineage: the reference's per-tuple host data path — Source allocating a
// tuple per record (wf/source.hpp:184), Shipper copying per push (wf/shipper.hpp:87),
// Standard_Emitter hashing every key (wf/standard_emitter.hpp:88-99, std::hash) —
// is the cost the micro-batch design removes. This module is that path's native
// counterpart for the TPU host: records arriving AoS (network/disk framing) are
// transposed to SoA columns in one C pass, and string/integer keys are hashed to
// key slots with the exact arithmetic of windflow_tpu.batch.hash_key_to_slot
// (32-bit FNV-1a for strings, Knuth uint64 multiply for ints), so host-ingested
// and device-generated streams agree on key routing bit-for-bit.
//
// C ABI for ctypes (pybind11 is not in this image). All pointers are caller-owned;
// no allocation happens in this module.

#include <cstdint>
#include <cstring>

extern "C" {

// AoS -> SoA: scatter n_fields interleaved fields of each of n records into
// contiguous per-field columns. src is the record buffer (record i at
// src + i*stride); field f occupies sizes[f] bytes at offsets[f] within a record
// and lands in dst[f] + i*sizes[f]. Fast paths for the power-of-two widths cover
// every numeric dtype; memcpy handles packed structs/strings.
void wf_unpack_records(const char* src, uint64_t n, uint64_t stride,
                       uint32_t n_fields, const uint64_t* offsets,
                       const uint64_t* sizes, char** dst) {
    for (uint32_t f = 0; f < n_fields; ++f) {
        const char* s = src + offsets[f];
        char* d = dst[f];
        const uint64_t w = sizes[f];
        switch (w) {
        case 1:
            for (uint64_t i = 0; i < n; ++i) d[i] = s[i * stride];
            break;
        case 2:
            for (uint64_t i = 0; i < n; ++i)
                std::memcpy(d + i * 2, s + i * stride, 2);
            break;
        case 4:
            for (uint64_t i = 0; i < n; ++i)
                std::memcpy(d + i * 4, s + i * stride, 4);
            break;
        case 8:
            for (uint64_t i = 0; i < n; ++i)
                std::memcpy(d + i * 8, s + i * stride, 8);
            break;
        default:
            for (uint64_t i = 0; i < n; ++i)
                std::memcpy(d + i * w, s + i * stride, w);
        }
    }
}

// SoA -> AoS (egress symmetric of the above: sinks emitting framed records).
void wf_pack_records(char* dst, uint64_t n, uint64_t stride, uint32_t n_fields,
                     const uint64_t* offsets, const uint64_t* sizes,
                     const char* const* src) {
    for (uint32_t f = 0; f < n_fields; ++f) {
        char* d = dst + offsets[f];
        const char* s = src[f];
        const uint64_t w = sizes[f];
        for (uint64_t i = 0; i < n; ++i)
            std::memcpy(d + i * stride, s + i * w, w);
    }
}

// 32-bit FNV-1a over [offsets[i], offsets[i+1]) byte ranges, modulo num_slots —
// bit-identical to windflow_tpu.batch._fnv1a / hash_key_to_slot for str/bytes.
void wf_hash_str_keys(const char* buf, const int64_t* offsets, uint64_t n,
                      uint32_t num_slots, int32_t* out) {
    for (uint64_t i = 0; i < n; ++i) {
        uint32_t h = 2166136261u;
        for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h ^= static_cast<unsigned char>(buf[j]);
            h *= 16777619u;
        }
        out[i] = static_cast<int32_t>(h % num_slots);
    }
}

// Fixed-width string keys (numpy 'S<w>' column, NUL-padded): hash each record's
// value with TRAILING NULs stripped but embedded NULs kept — numpy's own
// bytes-item semantics, so binary keys route identically to the Python fallback.
// AoS form: key i at buf + i*stride.
void wf_hash_fixed_str_keys(const char* buf, uint64_t n, uint64_t stride,
                            uint64_t width, uint32_t num_slots, int32_t* out) {
    for (uint64_t i = 0; i < n; ++i) {
        const char* s = buf + i * stride;
        uint64_t len = width;
        while (len > 0 && s[len - 1] == '\0') --len;
        uint32_t h = 2166136261u;
        for (uint64_t j = 0; j < len; ++j) {
            h ^= static_cast<unsigned char>(s[j]);
            h *= 16777619u;
        }
        out[i] = static_cast<int32_t>(h % num_slots);
    }
}

// Knuth multiplicative hash in uint64 wraparound — matches the integer branch of
// hash_key_to_slot ((k * 2654435761) mod 2^64 mod num_slots).
void wf_hash_int_keys(const int64_t* keys, uint64_t n, uint32_t num_slots,
                      int32_t* out) {
    for (uint64_t i = 0; i < n; ++i) {
        uint64_t k = static_cast<uint64_t>(keys[i]) * 2654435761ull;
        out[i] = static_cast<int32_t>(k % num_slots);
    }
}

}  // extern "C"
