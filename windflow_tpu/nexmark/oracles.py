"""Dense host-side oracles for every Nexmark query — exact expected
outputs, computed with plain Python loops over the event definitions (the
``tests/test_ysb.py`` oracle style: no JAX, no shared device code paths, so
a bug in the batched operators cannot hide in its own oracle).

Event model (mirrors :mod:`generators`, re-derived independently here):
``ts(i) = i // EVENTS_PER_TICK``; bid fields are modular functions of the
event index. All oracles return sorted lists of plain tuples; the tests
compare them against the sorted sink captures.
"""

from __future__ import annotations

from . import queries as q
from .generators import (EVENTS_PER_TICK, N_AUCTIONS, N_BIDDERS,
                         N_CATEGORIES, OPEN_EVERY, PRICE_MOD)


def _ts(i):
    return i // EVENTS_PER_TICK


def _auction(i):
    return (i * 2477) % N_AUCTIONS


def _bidder(i):
    return ((i % 7) * (i % 11) + i // 13) % N_BIDDERS


def _price(i):
    return (i * 7919) % PRICE_MOD + 100


def q1_currency(total):
    """[(id, auction, euro)] for every bid."""
    return sorted((i, _auction(i), _price(i) * q.EURO_NUM // q.EURO_DEN)
                  for i in range(total))


def q2_selection(total):
    """[(id, auction, price)] for bids on selected auctions."""
    return sorted((i, _auction(i), _price(i)) for i in range(total)
                  if _auction(i) % q.SELECT_MOD == 0)


def q3_enrich_join(total):
    """[(id, auction, category, price)] for every bid (definitions precede
    all bids, so every probe hits)."""
    out = []
    for i in range(N_AUCTIONS, total):
        a = _auction(i)
        out.append((i, a, (a * 13) % N_CATEGORIES, _price(i)))
    return sorted(out)


def q4_interval_join(total):
    """[(auction, open_ts, bid_ts, price)] for every (open, bid) pair of
    the same auction with ``bid_ts - open_ts in [0, JOIN_WINDOW]``."""
    opens, bids = [], []
    for i in range(total):
        if i % OPEN_EVERY == 0:
            opens.append(((i // OPEN_EVERY) % N_AUCTIONS, _ts(i)))
        else:
            bids.append((_auction(i), _ts(i), _price(i)))
    out = []
    for a, ots in opens:
        for b, bts, p in bids:
            if a == b and 0 <= bts - ots <= q.JOIN_WINDOW:
                out.append((a, ots, bts, p))
    return sorted(out)


def q5_session(total):
    """[(bidder, ordinal, start, end, n, bids, spend)] per closed session
    (gap-chained in event time per bidder)."""
    per_key = {}
    for i in range(total):
        per_key.setdefault(_bidder(i), []).append((_ts(i), _price(i)))
    out = []
    for k, events in per_key.items():
        ordinal = 0
        start, end, n, spend = None, None, 0, 0
        for ts, p in events:                    # already event-time ordered
            if start is None:
                start, end, n, spend = ts, ts, 1, p
            elif ts - end <= q.SESSION_GAP:
                end, n, spend = max(end, ts), n + 1, spend + p
            else:
                out.append((k, ordinal, start, end, n, n, spend))
                ordinal += 1
                start, end, n, spend = ts, ts, 1, p
        if start is not None:
            out.append((k, ordinal, start, end, n, n, spend))
    return sorted(out)


def q6_topn(total):
    """[(auction, rank, id, price)] — the final top-N leaderboard."""
    per_key = {}
    for i in range(total):
        per_key.setdefault(_auction(i), []).append((-_price(i), i))
    out = []
    for a, cands in per_key.items():
        for rank, (np_, i) in enumerate(sorted(cands)[:q.TOP_N]):
            out.append((a, rank, i, -np_))
    return sorted(out)


def q7_distinct(total):
    """[(id, auction)] — the first bid of each selected auction."""
    seen, out = set(), []
    for i in range(total):
        a = _auction(i)
        if a % q.SELECT_MOD == 0 and a not in seen:
            seen.add(a)
            out.append((i, a))
    return sorted(out)


ORACLES = {
    "q1_currency": q1_currency,
    "q2_selection": q2_selection,
    "q3_enrich_join": q3_enrich_join,
    "q4_interval_join": q4_interval_join,
    "q5_session": q5_session,
    "q6_topn": q6_topn,
    "q7_distinct": q7_distinct,
}
