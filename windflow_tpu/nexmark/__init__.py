"""Nexmark-style benchmark suite — the workload face of the join/session/
rank operator family.

The Nexmark continuous-query benchmark (auctions / bids / persons) is the
standard scenario battery beyond YSB; this package carries a TPU-native
restatement sized to the framework's micro-batch model:

- :mod:`generators` — synthetic on-device event sources (bid stream, tagged
  auction+bid streams for the join queries), all ``DeviceSource`` fast-path
  (generation fuses into the compiled chain, zero H2D).
- :mod:`queries` — one builder per query in
  ``observability/names.py::NEXMARK_QUERIES`` (currency-map, selection-
  filter, stream-table enrichment join, interval join, session aggregate,
  top-N-by-key, distinct), each returning ``(source, ops)`` ready for any
  driver.
- :mod:`oracles` — dense host-side oracles (exact expected outputs, the
  ``tests/test_ysb.py`` style) for every query.

Wired into ``bench.py::bench_nexmark``, ``benchmarks/sweep.py`` and the
hermetic perf gate (``analysis/perfgate.py`` ``nexmark_*`` cost pins) so
every query lands in the capture + trend machinery.
"""

from . import generators, oracles, queries
from .queries import QUERIES, make_query

__all__ = ["generators", "oracles", "queries", "QUERIES", "make_query"]
