"""Nexmark event generators — deterministic on-device synthetic streams.

All sources are ``DeviceSource`` (generation fuses into the compiled chain).
Event-time advances ``EVENTS_PER_TICK`` events per tick, the YSB convention.
The tagged sources interleave two logical streams into ONE schema-unified
stream (``side`` payload field), which is exactly the shape a two-input
``PipeGraph`` merge produces — so the same queries run single-pipe (the
bench/test fast path) or as genuine two-pipe merges (``MultiPipe.
join_with``).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..operators.source import DeviceSource

EVENTS_PER_TICK = 8     # ts = i // EVENTS_PER_TICK
N_AUCTIONS = 16
N_BIDDERS = 8
N_CATEGORIES = 7
PRICE_MOD = 9973        # pseudo-random bid price: (i * 7919) % PRICE_MOD + 100
OPEN_EVERY = 16         # every OPEN_EVERY-th event of the tagged join stream
                        # opens an auction (the interval-join left side)


def _i32(x):
    return jnp.asarray(x, jnp.int32)


def bid_auction(i):
    return (i * 2477) % N_AUCTIONS


def bid_bidder(i):
    # deliberately irregular per-bidder inter-arrival times: session gaps
    # must be data-dependent, not a fixed lattice
    return ((i % 7) * (i % 11) + i // 13) % N_BIDDERS


def bid_price(i):
    return (i * 7919) % PRICE_MOD + 100


def make_bid_source(total: int, name: str = "nexmark_bids") -> DeviceSource:
    """The plain bid stream: ``{auction, bidder, price}`` keyed by auction."""
    def gen(i):
        return {"auction": _i32(bid_auction(i)),
                "bidder": _i32(bid_bidder(i)),
                "price": _i32(bid_price(i))}
    return DeviceSource(gen, total=total, name=name,
                        key_fn=lambda i: bid_auction(i),
                        ts_fn=lambda i: i // EVENTS_PER_TICK)


def make_enrich_source(total: int, name: str = "nexmark_enrich",
                       n_auctions: int = N_AUCTIONS) -> DeviceSource:
    """Tagged stream for the stream-table join: events ``0..n_auctions-1``
    are auction definitions (``side == 1``, ``category`` set), the rest are
    bids (``side == 0``). Definitions strictly precede every bid in event
    time, so probe results are invariant to batching (the as-of-watermark
    read sees every definition). ``n_auctions`` scales the key space — the
    tiered-state acceptance workload runs this source at 100x the default
    cardinality with the hot table unchanged."""
    n_auctions = int(n_auctions)

    def gen(i):
        is_def = i < n_auctions
        auction = jnp.where(is_def, i, (i * 2477) % n_auctions)
        return {"side": jnp.where(is_def, 1, 0).astype(jnp.int32),
                "auction": _i32(auction),
                "category": jnp.where(is_def, (i * 13) % N_CATEGORIES,
                                      0).astype(jnp.int32),
                "price": jnp.where(is_def, 0,
                                   bid_price(i)).astype(jnp.int32)}
    return DeviceSource(gen, total=total, name=name,
                        key_fn=lambda i: jnp.where(i < n_auctions, i,
                                                   (i * 2477) % n_auctions),
                        ts_fn=lambda i: i // EVENTS_PER_TICK)


def make_open_bid_source(total: int,
                         name: str = "nexmark_open_bid") -> DeviceSource:
    """Tagged stream for the interval join: every ``OPEN_EVERY``-th event
    opens an auction (``side == 1``), the rest are bids — a bid matches an
    open of the same auction within the join's ``[0, upper]`` tick window."""
    def gen(i):
        is_open = (i % OPEN_EVERY) == 0
        auction = jnp.where(is_open, (i // OPEN_EVERY) % N_AUCTIONS,
                            bid_auction(i))
        return {"side": jnp.where(is_open, 1, 0).astype(jnp.int32),
                "auction": _i32(auction),
                "price": jnp.where(is_open, 0,
                                   bid_price(i)).astype(jnp.int32)}
    def key(i):
        is_open = (i % OPEN_EVERY) == 0
        return jnp.where(is_open, (i // OPEN_EVERY) % N_AUCTIONS,
                         bid_auction(i))
    return DeviceSource(gen, total=total, name=name, key_fn=key,
                        ts_fn=lambda i: i // EVENTS_PER_TICK)
