"""Nexmark query builders — one per ``names.py::NEXMARK_QUERIES`` entry.

Each builder returns ``(source, ops)``: attach any sink and run under any
driver (plain / threaded / supervised / graph). Defaults are sized for
correctness tests; bench/perf-gate callers pass their own ``total``.

Query map (the classic Nexmark numbers, restated for this event model):

====================  ===================================================
q1_currency           per-bid dollar -> euro projection (currency map)
q2_selection          selection filter: auctions of interest
q3_enrich_join        stream-table join: bid enriched with its auction's
                      category through the versioned JoinTable (the
                      registry ``join_probe`` production call site)
q4_interval_join      interval join: bid matches an auction-open event of
                      the same auction within ``[0, JOIN_WINDOW]`` ticks
q5_session            session aggregate: per-bidder bid count + price sum
                      per activity session (gap ``SESSION_GAP`` ticks)
q6_topn               incremental top-``TOP_N`` bid prices per auction
q7_distinct           first bid per selected auction (distinct)
====================  ===================================================
"""

from __future__ import annotations

import jax.numpy as jnp

from ..observability.names import NEXMARK_QUERIES as QUERIES
from ..operators.filter import Filter
from ..operators.join import IntervalJoin, StreamTableJoin
from ..operators.map import KeyBy, Map
from ..operators.rank import Distinct, TopN
from ..operators.session import SessionWindow
from ..operators.window import WindowSpec
from . import generators as g

#: euro conversion: integer, exact (the reference multiplies by 0.89)
EURO_NUM, EURO_DEN = 89, 100
#: q2/q7 selection predicate: auctions divisible by this
SELECT_MOD = 4
#: q4 interval-join window, ticks
JOIN_WINDOW = 4
#: q5 session gap, ticks
SESSION_GAP = 2
#: q6 leaderboard depth
TOP_N = 3


def q1_currency(total: int):
    src = g.make_bid_source(total)
    ops = [Map(lambda t: {"auction": t.auction,
                          "euro": (t.price * EURO_NUM) // EURO_DEN},
               name="nexmark_currency")]
    return src, ops


def q2_selection(total: int):
    src = g.make_bid_source(total)
    ops = [Filter(lambda t: t.auction % SELECT_MOD == 0,
                  name="nexmark_select")]
    return src, ops


def q3_enrich_join(total: int, n_auctions: int = g.N_AUCTIONS,
                   num_slots: int = None, tiered=None):
    """``n_auctions`` scales the key space (the 100x tiered acceptance
    workload); ``num_slots`` pins the HOT table size independently of the
    key space; ``tiered=`` opts the JoinTable into the two-tier state
    layer (``windflow_tpu/state``)."""
    src = g.make_enrich_source(total, n_auctions=n_auctions)
    ops = [StreamTableJoin(
        lambda t: t.side == 1,                 # auction definitions build
        lambda t: t.auction,
        lambda t: {"category": t.category},    # the enrichment column
        num_slots=int(num_slots if num_slots is not None else n_auctions),
        tiered=tiered, name="nexmark_enrich_join")]
    return src, ops


def q4_interval_join(total: int, max_matches: int = 8, tiered=None):
    src = g.make_open_bid_source(total)
    ops = [IntervalJoin(
        lambda t: t.side == 1,                 # auction opens are the left
        0, JOIN_WINDOW, max_matches=max_matches, tiered=tiered,
        emit=lambda l, r: {"auction": l.data["auction"],
                           "open_ts": l.ts, "bid_ts": r.ts,
                           "price": r.data["price"]},
        name="nexmark_interval_join")]
    return src, ops


def q5_session(total: int, tiered=None):
    src = g.make_bid_source(total)
    ops = [KeyBy(lambda t: t.bidder, g.N_BIDDERS, name="nexmark_by_bidder"),
           SessionWindow(lambda t: {"bids": jnp.ones((), jnp.int32),
                                    "spend": t.price},
                         WindowSpec.session(SESSION_GAP),
                         num_keys=g.N_BIDDERS, tiered=tiered,
                         name="nexmark_session")]
    return src, ops


def q6_topn(total: int, tiered=None):
    src = g.make_bid_source(total)
    ops = [TopN(lambda t: t.price, TOP_N, num_keys=g.N_AUCTIONS,
                tiered=tiered, name="nexmark_topn")]
    return src, ops


def q7_distinct(total: int, tiered=None):
    src = g.make_bid_source(total)
    ops = [Filter(lambda t: t.auction % SELECT_MOD == 0,
                  name="nexmark_select"),
           Distinct(lambda t: t.auction, num_slots=g.N_AUCTIONS,
                    tiered=tiered, name="nexmark_distinct")]
    return src, ops


_BUILDERS = {
    "q1_currency": q1_currency,
    "q2_selection": q2_selection,
    "q3_enrich_join": q3_enrich_join,
    "q4_interval_join": q4_interval_join,
    "q5_session": q5_session,
    "q6_topn": q6_topn,
    "q7_distinct": q7_distinct,
}

assert set(_BUILDERS) == set(QUERIES), "queries drifted from names.py"


def make_query(name: str, total: int, **kw):
    """``(source, ops)`` for one registered query name."""
    return _BUILDERS[name](total, **kw)
