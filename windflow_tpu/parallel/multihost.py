"""Multi-host scale-out: distributed initialization + DCN×ICI mesh construction.

The reference's process boundary is one shared-memory process (SURVEY §1: no
MPI/NCCL/sockets anywhere in ``wf/``); its scale ceiling is one machine. The
TPU-native generalization runs one process per host over ``jax.distributed`` with a
two-level mesh: the OUTER axis spans hosts over DCN (slow, collective-light), the
INNER axes span each host's chips over ICI (fast, collective-heavy). The framework's
axis taxonomy (``parallel/mesh.py``) maps on as:

- ``dp`` (batch capacity / operator replication) → DCN-safe: each host's source
  ingests its own stream partition; no cross-host traffic except at keyed shuffles.
- ``key`` (Key_Farm state tables) → ICI by default; spanning DCN is correct but the
  ``keyed_all_to_all`` exchange then rides DCN — size lane budgets accordingly.
- ``win`` / ``part`` (window/partition axes, `ring_pane_windows`/`wmr_map_reduce`)
  → keep INSIDE a host (ICI): their per-step halo/all-reduce latency is the window
  emission latency.

Usage (one process per host, e.g. under a pod scheduler)::

    from windflow_tpu.parallel import multihost
    multihost.initialize()                      # no-op single-process
    mesh = multihost.make_dcn_ici_mesh(dcn_axis="dp", ici_axes=("key",))
    # -> Mesh over all hosts x all local chips; shard states/batches as usual

Single-process fallback: every helper degrades to the local-devices mesh so the same
program text runs from a laptop test to a pod (tested on the virtual CPU mesh).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh

from .mesh import make_mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Initialize ``jax.distributed`` when running multi-process; no-op (returns
    False) when single-process or already initialized. Arguments default to the
    standard env-based auto-detection (JAX_COORDINATOR_ADDRESS etc.).

    The already-initialized probe reads the distributed client handle, NOT
    ``jax.process_count()`` — querying the backend would itself initialize it,
    after which ``jax.distributed.initialize`` is too late (2-process smoke
    test caught exactly that)."""
    try:                                          # private module path: may move
        from jax._src import distributed as _dist
        if getattr(_dist.global_state, "client", None) is not None:
            return False                          # already initialized
    except (ImportError, AttributeError):
        pass  # fall through: initialize() below raises if already initialized
    if coordinator_address is None and num_processes is None:
        import os
        if "JAX_COORDINATOR_ADDRESS" not in os.environ:
            return False                          # single-process run
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return True
    except RuntimeError:                          # already initialized
        return False


def make_dcn_ici_mesh(dcn_axis: str = "dp",
                      ici_axes: Sequence[str] = ("key",),
                      ici_shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Two-level mesh: ``dcn_axis`` spans processes (hosts), ``ici_axes`` span each
    process's local chips. Uses ``mesh_utils.create_hybrid_device_mesh`` when
    multi-process (respects DCN/ICI topology); degrades to a flat local mesh with
    the same axis names single-process, so programs are textually identical."""
    n_proc = jax.process_count()
    local = jax.local_device_count()
    if ici_shape is None:
        ici_shape = _factor(local, len(ici_axes))
    if n_proc > 1:
        try:
            from jax.experimental import mesh_utils
            devs = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=ici_shape,
                dcn_mesh_shape=(n_proc,) + (1,) * (len(ici_shape) - 1))
            # hybrid mesh returns [dcn*ici0, ici1, ...]; reshape to (dcn, *ici)
            devs = devs.reshape((n_proc,) + tuple(ici_shape))
        except ValueError:
            # backends without slice topology info (e.g. multi-process CPU):
            # the DCN grouping is by owning process, which is what the outer
            # axis means — row i = process i's local devices
            devs = np.array(sorted(jax.devices(),
                                   key=lambda d: (d.process_index, d.id)))
            devs = devs.reshape((n_proc,) + tuple(ici_shape))
        return Mesh(devs, (dcn_axis,) + tuple(ici_axes))
    devs = np.array(jax.devices()).reshape((1,) + tuple(ici_shape))
    return Mesh(devs, (dcn_axis,) + tuple(ici_axes))


def _factor(n: int, k: int) -> Tuple[int, ...]:
    """Split n into k near-balanced power-of-two-ish factors (largest first)."""
    if k == 1:
        return (n,)
    f = 1
    target = round(n ** (1 / k))
    for c in range(target, 0, -1):
        if n % c == 0:
            f = c
            break
    return (n // f,) + _factor(f, k - 1) if k == 2 else (f,) + _factor(n // f, k - 1)


def process_shard_slice(num_shards: int) -> Tuple[int, int]:
    """Contiguous shard-index range ``[lo, hi)`` supervised by THIS process —
    the multi-host layout of the shard-local supervision layer
    (``runtime/supervisor.py`` ``ShardedSupervisor``): process ``i`` of ``P``
    owns shards ``[i*ceil(N/P), ...)``, so each host runs its own
    per-shard recovery domains over its own key ranges (pass the slice as
    ``SupervisedPipeline(shards=N, shard_range=...)``) and writes its own
    per-shard checkpoint files — a failed host's peers keep serving their
    shards, which is the whole point. Degenerates to ``(0, num_shards)``
    single-process."""
    p, i = jax.process_count(), jax.process_index()
    per = -(-int(num_shards) // p)
    lo = min(i * per, int(num_shards))
    hi = min(lo + per, int(num_shards))
    return lo, hi


def process_local_batch_range(total: int, batch_size: int) -> Tuple[int, int]:
    """Partition a global stream of ``total`` tuples across processes: each host's
    source generates/ingests only its contiguous share (the multi-host Source
    replication rule — reference Source replicas split the stream the same way
    in-process, ``wf/source.hpp:284-296``)."""
    p, i = jax.process_count(), jax.process_index()
    per = -(-total // p)
    lo = min(i * per, total)
    hi = min(lo + per, total)
    # round the share to whole batches so every host steps in lockstep
    lo -= lo % batch_size
    return lo, hi
