"""Ordering — deterministic order restoration at merge/shuffle boundaries.

Counterpart of ``Ordering_Node`` (``wf/ordering_node.hpp:47-287``): the reference
buffers tuples per key in priority queues and releases those at or below the
*low-watermark* — the minimum over all input channels of the maximum id/ts seen
(``maxs[]`` logic, ``:79-94``). The batch-level restatement:

- each input channel advances a watermark = max (ts or id) of the batches it has
  delivered;
- buffered batches are merged, stably sorted by (ts, id) (or (id,)), and the
  provably-complete prefix is released, the rest retained. ID mode releases
  sort-key <= min(channel watermarks) like the reference (a channel's ids
  strictly increase, so watermark ties cannot recur); TS modes release strictly
  BELOW the low watermark — a channel may deliver more tuples EQUAL to its own
  watermark, and releasing those ties early would leak poll interleaving into
  the output order. Channel EOS lifts that channel's gate entirely.

Modes mirror ``ordering_mode_t`` (``wf/basic.hpp:129``): ID, TS, TS_RENUMBERING
(released tuples are renumbered with a progressive id — used by DETERMINISTIC
count-based windows downstream, ``wf/pipegraph.hpp:1954-1957``).

Hot-path cost (VERDICT r03 weak #4, r04 weak #2): the pending pool is kept
PHYSICALLY SORTED as an invariant (live lanes ascending by the composite key,
invalid lanes at the tail — the release split and the trim both preserve it),
so a push never re-sorts the pool. Each push is ONE jitted dispatch that:

1. updates the channel watermark on device (``.at[channel].max``),
2. sorts only the INCOMING batch (O(B log^2 B) on B rows, not the pool),
3. merges it with the sorted backlog via a bitonic merge network —
   log2(pool+batch) vectorized compare-exchange stages over the composite keys
   (the reference pays O(log n) per tuple in per-key priority queues,
   ``wf/ordering_node.hpp:79-94``; this is the data-parallel restatement).
   The network is the ``"ordering_merge"`` kernel of the per-backend registry
   (``ops/registry.py``): ``xla`` = per-stage fused ops (``ops/bitonic.py::
   merge_network``), ``pallas`` = all stages in ONE kernel, keys
   VMEM-resident (``merge_network_pallas``) — resolved once per node at
   construction, byte-identical either way,
4. releases the provably-complete PREFIX with one elementwise compare (no sort),
5. renumbers on device in TS_RENUMBERING mode (``_next_id`` is a device scalar).

The host reads back exactly ONE tiny transfer per push — the packed
``[n_released, n_kept]`` counts, which also feed the backlog trim and (via
``last_release_count``) the driver's chunker, so no second sync follows.
And that one transfer is SYNC-FREE on the push path itself: ``push``/
``try_release`` start the readback with ``copy_to_host_async`` the moment the
core is dispatched and return the released batch immediately (possibly with
zero valid lanes — callers chunk by ``last_release_count``, so an empty
release flows through untouched). The blocking ``int()`` is deferred until
the counts are actually consulted — ``last_release_count`` is a property that
settles the pending transfer and applies the owed backlog trim. The realized
win is per-push latency, not overlap across pushes (today's callers consult
the count right after the push): the D2H is enqueued on the device stream
directly behind the core's compute instead of being REQUESTED by the host
after it has already blocked — the consult pays the residual compute time
only, not compute plus a host-initiated synchronous round trip (~65 us RTT
on the tunneled dev chip, per push). ``flush``/``close_channel``
(EOS-granular) stay synchronous.

The jitted cores are MODULE-LEVEL functions cached per mode (not per-instance
``jax.jit`` wrappers): every Ordering_Node a graph constructs shares one trace
and one compile per (mode, shapes) — a fresh PipeGraph pays zero re-trace.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import ordering_mode_t
from ..batch import Batch, CTRL_DTYPE

#: "no watermark yet" sentinel — gates the low-watermark on device exactly like
#: the host-side ``None`` it replaces (a channel at the sentinel keeps
#: ``min(wm)`` at the sentinel, and the release predicate masks on that).
#: Edge (documented like the dtype-max edge in ``close_channel``): the sentinel
#: aliases the legal key value ``iinfo(CTRL_DTYPE).min`` — a channel whose valid
#: tuples all carry ts/id == dtype-min never advances past the sentinel
#: (``.max`` from the sentinel is a no-op), so in DETERMINISTIC mode it gates
#: all releases until the channel closes. Keys at the extreme ends of the i32
#: domain are outside the supported key range; ``flush``/``close_channel``
#: still deliver such tuples at EOS.
WM_NONE = jnp.iinfo(CTRL_DTYPE).min

_BIG = jnp.iinfo(CTRL_DTYPE).max


def _lex_lt(a: Tuple, b: Tuple):
    """Strict lexicographic < over equal-length tuples of i32 arrays."""
    out = None
    eq = None
    for x, y in zip(a, b):
        term = (x < y) if eq is None else (eq & (x < y))
        out = term if out is None else (out | term)
        eq = (x == y) if eq is None else (eq & (x == y))
    return out


# -- mode-parameterized jitted cores (shared across ALL Ordering_Node instances) --------

def _sort_keys(mode, b: Batch, chan):
    """(primary, secondary, tertiary) composite sort: id/ts, then the other
    control field, then source channel — a TOTAL deterministic order even when
    two channels carry equal (ts, id) pairs (poll interleaving must not leak
    into release order)."""
    prim = b.id if mode == ordering_mode_t.ID else b.ts
    sec = b.ts if mode == ordering_mode_t.ID else b.id
    return prim, sec, chan


def _masked_keys(mode, b: Batch, chan):
    """Composite key with invalid lanes forced to (+max, +max, +max) so they
    sort to the tail in a well-defined order."""
    prim, sec, tert = _sort_keys(mode, b, chan)
    v = b.valid
    return (jnp.where(v, prim, _BIG), jnp.where(v, sec, _BIG),
            jnp.where(v, tert, _BIG))


def _bitonic_merge(prim, sec, chan, idx, impl: str = "xla"):
    """Merge a bitonic (ascending++descending) composite-key sequence into
    ascending order: log2(n) vectorized compare-exchange stages. ``idx`` is
    the unique position tie-break (making the order total) AND the gather
    index that moves the actual rows once at the end.

    The network itself lives in ``ops/bitonic.py`` (the ``"ordering_merge"``
    registry kernel): ``impl="xla"`` is the per-stage reshape+select form
    (77x faster than a pos^d gather on the CPU backend — 0.28 ms vs 21.7 ms
    at n=8192; XLA fuses slicing/wheres but lowers dynamic gathers to scalar
    loops), ``impl="pallas"`` fuses ALL stages into one kernel whose key
    arrays never leave VMEM. Byte-identical by construction — both run the
    same compare-exchange plan."""
    from ..ops import bitonic
    merge = (bitonic.merge_network_pallas if impl == "pallas"
             else bitonic.merge_network)
    return merge(prim, sec, chan, idx)


def _wm_after(mode, wm, channel, batch: Batch):
    k = batch.id if mode == ordering_mode_t.ID else batch.ts
    mx = jnp.max(jnp.where(batch.valid, k, WM_NONE))
    return wm.at[channel].max(mx)


def _split_release(mode, sortedb: Batch, chan_s, wm, next_id,
                   release_all: bool):
    """Release decision on an ALREADY-SORTED pool: one elementwise compare,
    no sort. Returns (out, kept, kept_chan, counts[2], next_id). ``kept`` is
    re-compacted (live lanes to the front) with one O(N) roll — on the
    sorted pool the released lanes are exactly a physical prefix, so rolling
    left by ``n_released`` restores the invariant the next merge needs."""
    if release_all:
        # EOS: every valid lane goes, sorted. No watermark compare — a
        # valid sort-key equal to the dtype max is indistinguishable from
        # the invalid-lane sentinel, so any threshold would either drop it
        # or resurrect dead lanes.
        releasable = sortedb.valid
    else:
        low_wm = jnp.min(wm)
        ks = jnp.where(sortedb.valid, _sort_keys(mode, sortedb, chan_s)[0],
                       _BIG)
        # ID mode: a channel's ids strictly increase, so ties AT the
        # watermark cannot arrive again — release `<=` like the reference
        # (wf/ordering_node.hpp:197 `id > min_id` break). TS modes: a
        # channel may deliver MORE tuples equal to its own watermark, so
        # releasing ties at the low watermark would leak poll interleaving
        # into the output order (fuzz-caught); hold them until every
        # watermark strictly passes.
        if mode == ordering_mode_t.ID:
            releasable = ks <= low_wm
        else:
            releasable = ks < low_wm
        # a channel still at the WM_NONE sentinel gates everything — the
        # device-side restatement of the old host `any(w is None)` check
        releasable &= low_wm != WM_NONE
        releasable &= sortedb.valid
    out = sortedb.mask(releasable)
    kept = sortedb.mask(sortedb.valid & ~releasable)
    n_out = jnp.sum(out.valid.astype(CTRL_DTYPE))
    roll = lambda a: jnp.roll(a, -n_out, axis=0)
    kept = jax.tree.map(roll, kept)
    kept_chan = roll(chan_s)
    if mode == ordering_mode_t.TS_RENUMBERING:
        ids = jnp.cumsum(out.valid.astype(CTRL_DTYPE)) - 1 + next_id
        out = out.replace(id=jnp.where(out.valid, ids, out.id))
        next_id = next_id + n_out
    counts = jnp.stack([n_out, jnp.sum(kept.valid.astype(CTRL_DTYPE))])
    return out, kept, kept_chan, counts, next_id


def _sort_batch(mode, batch: Batch, chan, merge_impl: str = "xla"):
    """Stable ascending sort of one batch by the composite key (invalid to
    the tail). Returns (sorted keys..., data-order permutation).

    Fast path: sources deliver batches in ts/id order with the invalid tail
    already last, so the masked composite key is usually ALREADY ascending —
    a 0.02 ms elementwise check gates the 1.0 ms lexsort (measured, CPU
    backend, B=4096; the reference's per-key pqs get the same win implicitly
    because ordered arrivals insert at the heap root, ``wf/ordering_node.hpp:
    79-94``). Both branches are value-identical on sorted input (stable
    lexsort of a sorted sequence is the identity permutation), so the
    data-dependent cond cannot leak into output order.

    The 2x DETERMINISTIC win is measured IN-CHAIN on the CPU backend
    (bench_ordering_overhead), so XLA:CPU does not flatten this cond into
    select-both-branches; whether XLA:TPU does is A/B-able without code
    changes via ``WF_ORDERING_SKIP_SORTED=0`` (re-enables the unconditional
    lexsort) — the same diagnostic pattern as WF_HISTOGRAM_FORCE_FAST."""
    import os
    bp, bs, bc = _masked_keys(mode, batch, chan)
    C = batch.capacity

    def dosort(_):
        if merge_impl == "pallas" and C >= 2 and C & (C - 1) == 0:
            # fused bitonic SORT network (ops/bitonic.py): the unique iota
            # tie-break makes the composite key total, so the network output
            # IS the stable lexsort permutation — byte-identical impls
            from ..ops.bitonic import sort_network_pallas
            iota = jnp.arange(C, dtype=jnp.int32)
            sp, ss, sc, order = sort_network_pallas(bp, bs, bc, iota)
            return sp, ss, sc, order
        order = jnp.lexsort((bc, bs, bp)).astype(jnp.int32)
        return bp[order], bs[order], bc[order], order

    if os.environ.get("WF_ORDERING_SKIP_SORTED", "1") == "0":
        return dosort(None)
    asc = ~_lex_lt((bp[1:], bs[1:], bc[1:]), (bp[:-1], bs[:-1], bc[:-1]))
    iota = jnp.arange(batch.capacity, dtype=jnp.int32)

    def ident(_):
        return bp, bs, bc, iota

    return jax.lax.cond(jnp.all(asc), ident, dosort, None)


def _first_push_core(mode, merge_impl, batch: Batch, channel, wm, next_id):
    """First push: no backlog — sort the batch, release the prefix."""
    wm = _wm_after(mode, wm, channel, batch)
    chan = jnp.full((batch.capacity,), channel, CTRL_DTYPE)
    _, _, _, order = _sort_batch(mode, batch, chan, merge_impl)
    sortedb = batch.select(order, jnp.ones_like(batch.valid))
    out, kept, kept_chan, counts, next_id = _split_release(
        mode, sortedb, chan, wm, next_id, False)
    return out, kept, kept_chan, counts, wm, next_id


def _push_core(mode, merge_impl, pending: Batch, pchan, batch: Batch,
               channel, wm, next_id):
    """The per-push hot path, one dispatch: watermark update + incoming-batch
    sort + bitonic merge with the sorted backlog + prefix release +
    renumbering. ``merge_impl`` (trace-time, resolved by the node through
    the kernel registry) routes the merge/sort networks: "xla" = per-stage
    fused ops, "pallas" = one kernel, keys VMEM-resident for all stages."""
    wm = _wm_after(mode, wm, channel, batch)
    P, B = pending.capacity, batch.capacity
    N = 1
    while N < P + B:
        N *= 2
    ap, asec, ac = _masked_keys(mode, pending, pchan)      # ascending already
    aidx = jnp.arange(P, dtype=jnp.int32)
    bchan = jnp.full((B,), channel, CTRL_DTYPE)
    bp, bs, bc, border = _sort_batch(mode, batch, bchan, merge_impl)
    bidx = P + border
    # pad the B side to N - P with +inf keys / garbage index, then reverse:
    # ascending(A) ++ descending(B) is bitonic for any split point
    pad = N - P - B
    ext = lambda a, fill: jnp.concatenate(
        [a, jnp.full((pad,), fill, a.dtype)])[::-1]
    prim = jnp.concatenate([ap, ext(bp, _BIG)])
    sec = jnp.concatenate([asec, ext(bs, _BIG)])
    chn = jnp.concatenate([ac, ext(bc, _BIG)])
    idx = jnp.concatenate([aidx, ext(bidx, P + B)])
    _, _, _, idx = _bitonic_merge(prim, sec, chn, idx, merge_impl)
    # one gather moves the rows: concat(pending, batch, 1 invalid garbage row)
    def take2(a, b):
        z = jnp.zeros((1,) + a.shape[1:], a.dtype)
        return jnp.take(jnp.concatenate([a, b, z], axis=0), idx, axis=0)
    merged = Batch(
        key=take2(pending.key, batch.key),
        id=take2(pending.id, batch.id),
        ts=take2(pending.ts, batch.ts),
        payload=jax.tree.map(take2, pending.payload, batch.payload),
        valid=jnp.take(
            jnp.concatenate([pending.valid, batch.valid,
                             jnp.zeros((1,), jnp.bool_)]), idx),
    )
    mchan = jnp.take(jnp.concatenate([pchan, bchan,
                                      jnp.zeros((1,), CTRL_DTYPE)]), idx)
    out, kept, kept_chan, counts, next_id = _split_release(
        mode, merged, mchan, wm, next_id, False)
    return out, kept, kept_chan, counts, wm, next_id


@functools.lru_cache(maxsize=None)
def _jitted_cores(mode: ordering_mode_t, merge_impl: str = "xla"):
    """One (push, first_push, release) jit triple per (mode, merge impl),
    shared by every Ordering_Node instance — construction of a fresh
    node/graph re-traces nothing. ``merge_impl`` is part of the cache key:
    the impl is baked into the traced program (the WF109 trace-time
    contract), so two impls coexist as two executables, never a retrace."""
    push = jax.jit(functools.partial(_push_core, mode, merge_impl))
    first = jax.jit(functools.partial(_first_push_core, mode, merge_impl))
    release = jax.jit(functools.partial(_split_release, mode),
                      static_argnums=(4,))
    return push, first, release


# every instance is confined to the ONE thread driving it — the pipeline
# driver, or the owning pipe thread of the threaded graph driver (role
# stage); the reporter deliberately reads `_last_release_count` raw and
# never calls into the node (metrics.py).  The WF26x concurrency lint
# checks this confinement: `settle` is annotated with the allowed roles
# below, and this class-level single-writer declaration is the recorded
# rationale for the lock-free mutable fields.
class Ordering_Node:  # wf-lint: single-writer[driver, stage]
    def __init__(self, n_inputs: int, mode: ordering_mode_t = ordering_mode_t.TS,
                 merge_impl: str = None):
        from ..ops.registry import resolve_impl
        self.n_inputs = int(n_inputs)
        self.mode = mode
        # kernel-registry selection at CONSTRUCTION time (= trace time for
        # the shared jitted cores); recorded for the WF109 staleness check
        self.merge_impl = resolve_impl("ordering_merge", impl=merge_impl,
                                       spec_key=f"mode={mode.name}")
        self._wm_dev = jnp.full((self.n_inputs,), WM_NONE, CTRL_DTYPE)
        self._pending: Optional[Batch] = None    # INVARIANT: sorted, invalid at tail
        self._pending_chan = None                # i32[C] source channel per lane
        self._next_id = jnp.zeros((), CTRL_DTYPE)   # device scalar (renumbering)
        self._last_release_count = 0
        #: packed [n_released, n_kept] device counts of the last push/
        #: try_release, D2H already in flight (copy_to_host_async), not yet
        #: int()ed; settled by ``last_release_count``/``settle`` — which also
        #: applies the backlog trim those counts size
        self._counts_pending = None
        self._push_jit, self._first_push_jit, self._release_jit = \
            _jitted_cores(mode, self.merge_impl)

    @property
    def last_release_count(self) -> int:
        """Valid-lane count of the batch last returned by push/try_release/
        flush — fetched with the (async) release counts, so drivers chunking
        the released batch need no second device sync. Reading it settles any
        in-flight counts readback; 0 whenever the last call released nothing
        (no stale value survives a no-release call)."""
        return self.settle()

    def settle(self) -> int:  # wf-lint: thread-role[driver, stage]
        """Force the deferred counts readback of the last push/try_release
        (a no-op when none is pending): int() the packed counts, apply the
        owed backlog trim, record ``last_release_count``. Called implicitly
        by the next push/try_release/flush and by the property above — the
        hot path itself never blocks between dispatch and return.

        OWNING-THREAD ONLY — and statically checked: the ``thread-role``
        annotation above restricts this API to the driver (or the one pipe
        thread that owns the node in the threaded graph driver); the WF261
        lint fails the gate if it ever becomes reachable from the reporter,
        a watchdog, a pool worker, or a JAX callback thread.  The
        check-then-settle is not atomic (the int() blocks on the device and
        releases the GIL), so a second settling thread could double-apply
        the pool trim. Off-thread readers (the metrics reporter) read
        ``_last_release_count`` raw instead."""
        counts = self._counts_pending
        if counts is not None:
            self._counts_pending = None
            n_out, n_kept = (int(x) for x in np.asarray(counts))
            self._last_release_count = n_out
            if self._pending is not None:
                self._trim_pow2(n_kept)
        return self._last_release_count

    def _defer_counts(self, counts) -> None:
        """Start the counts D2H without blocking: the transfer begins the
        moment the core's compute finishes (not at the eventual ``int()``),
        so the consult typically finds it already complete."""
        try:
            counts.copy_to_host_async()
        except AttributeError:      # np-backed counts (already host)
            pass
        self._counts_pending = counts

    # -- host protocol ----------------------------------------------------------------

    def push(self, channel: int, batch: Batch) -> Optional[Batch]:
        """Deliver a batch from ``channel``; returns the released (ordered)
        batch — possibly with ZERO valid lanes when nothing can be released
        yet (``last_release_count`` says which; chunking by it makes the
        empty case flow through untouched). One jitted dispatch, one packed
        [n_released, n_kept] readback — started async, settled only when the
        counts are consulted, so this call never blocks on the device."""
        self.settle()               # apply the trim owed by the previous call
        ch = jnp.asarray(channel, CTRL_DTYPE)
        if self._pending is None:
            out, kept, mchan, counts, wm, nid = self._first_push_jit(
                batch, ch, self._wm_dev, self._next_id)
        else:
            self._pad_pow2()
            out, kept, mchan, counts, wm, nid = self._push_jit(
                self._pending, self._pending_chan, batch, ch, self._wm_dev,
                self._next_id)
        self._wm_dev, self._next_id = wm, nid
        self._pending, self._pending_chan = kept, mchan
        self._defer_counts(counts)
        return out

    def resort_pending(self):
        """Re-establish the sorted-pool invariant on externally-assigned pending
        state (supervisor restore: snapshots from the pre-r05 design held the
        pool UNSORTED — the old code re-sorted at every release; the current
        merge/release assume ascending order with invalid lanes at the tail).
        Eager one-shot sort — a rare recovery path, not the hot path. Any
        in-flight counts readback is DISCARDED, not settled: it sized a pool
        that no longer exists (the restore overwrote it), and applying its
        trim to the assigned pool would corrupt it."""
        self._counts_pending = None
        if self._pending is None:
            return
        b, chan = self._pending, self._pending_chan
        bp, bs, bc = _masked_keys(self.mode, b, chan)
        order = jnp.lexsort((bc, bs, bp)).astype(jnp.int32)
        self._pending = b.select(order, jnp.ones_like(b.valid))
        self._pending_chan = jnp.take(chan, order)

    def _pad_pow2(self):
        """Pad the pending batch to a power-of-two capacity so the merge jit
        sees O(log max-backlog) distinct shapes instead of one per push.
        Padding appends invalid lanes at the tail — the sorted invariant holds."""
        b, chan = self._pending, self._pending_chan
        C = b.capacity
        P = 1
        while P < C:
            P *= 2
        if P == C:
            return
        pad = P - C

        def pz(a):
            return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        self._pending = Batch(key=pz(b.key), id=pz(b.id), ts=pz(b.ts),
                              payload=jax.tree.map(pz, b.payload),
                              valid=pz(b.valid))
        self._pending_chan = jnp.pad(chan, (0, pad))

    def _trim_pow2(self, n: int):
        """Trim the retained batch's capacity to the power of two covering the
        live count ``n`` (already fetched with the release counts — no sync
        here) — without this the padded kept capacity compounds with every merge
        (exponential growth); with it, capacities stay pow2 and bounded by ~2x
        the held-back backlog. The kept pool arrives COMPACTED (live lanes at
        the front — the roll in ``_split_release`` guarantees it), so the trim
        is a plain O(cap) head slice, not a sort."""
        b, chan = self._pending, self._pending_chan
        cap = 1
        while cap < max(n, 1):
            cap *= 2
        cap = max(cap, 64)
        if b.capacity <= cap:
            return

        def take(a):
            return a[:cap]
        self._pending = Batch(key=take(b.key), id=take(b.id), ts=take(b.ts),
                              payload=jax.tree.map(take, b.payload),
                              valid=take(b.valid))
        self._pending_chan = take(chan)

    def try_release(self) -> Optional[Batch]:
        """Release the prefix at or below the current low-watermark (the gating
        on channels without a watermark happens inside the jitted release via
        the WM_NONE sentinel). The pool is already sorted — this is one
        elementwise compare, no sort. Exactly ONE host readback: the packed
        [n_released, n_kept] counts — async like :meth:`push`, so the returned
        batch may have zero valid lanes (``last_release_count`` settles it);
        None only when there is no pool at all."""
        self.settle()
        if self._pending is None:
            self._last_release_count = 0
            return None
        out, kept, kept_chan, counts, nid = self._release_jit(
            self._pending, self._pending_chan, self._wm_dev, self._next_id,
            False)
        self._pending, self._pending_chan = kept, kept_chan
        self._next_id = nid
        self._defer_counts(counts)
        return out

    def _journal_release(self, event: str, **fields) -> None:
        """Emit an ordering-buffer event to the active journal (EOS-granular —
        close_channel / flush, never the per-push hot path)."""
        from ..observability import journal as _journal
        if _journal.get_active() is not None:
            _journal.record(event, mode=self.mode.name,
                            n_inputs=self.n_inputs,
                            released=self.last_release_count, **fields)

    def close_channel(self, channel: int) -> Optional[Batch]:
        """Channel EOS: it no longer gates the low-watermark (a liveness
        extension over the reference, whose ``eosnotify`` only flushes once ALL
        channels have closed — see the note below). Returns any batch the
        advanced watermark releases. The sentinel is the full dtype max, which
        un-gates the channel for everything below the max; a valid tuple AT the
        dtype max rides out with ``flush`` (whose release is unconditional on
        valid lanes) — mid-stream it is indistinguishable from the invalid-lane
        sentinel, so no watermark can free it.

        Reference relationship: ``wf/ordering_node.hpp`` ``eosnotify`` holds
        everything until every channel has delivered EOS, then flushes; the
        per-channel un-gating here releases the surviving channels' tuples as
        soon as a dead channel can no longer reorder them — same final order,
        earlier liveness."""
        self._wm_dev = self._wm_dev.at[channel].set(jnp.iinfo(CTRL_DTYPE).max)
        out = self.try_release()
        self._journal_release("ordering_close_channel", channel=channel)
        return out

    def flush(self) -> Optional[Batch]:
        """EOS: release everything, sorted (the pool already is). Synchronous
        — EOS-granular, not the hot path."""
        self.settle()
        if self._pending is None:
            self._last_release_count = 0
            self._journal_release("ordering_flush")
            return None
        out, _, _, counts, nid = self._release_jit(
            self._pending, self._pending_chan, self._wm_dev, self._next_id,
            True)
        self._pending, self._pending_chan = None, None
        self._next_id = nid
        self._last_release_count = int(np.asarray(counts)[0])
        self._journal_release("ordering_flush")
        return out
