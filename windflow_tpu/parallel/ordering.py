"""Ordering — deterministic order restoration at merge/shuffle boundaries.

Counterpart of ``Ordering_Node`` (``wf/ordering_node.hpp:47-287``): the reference
buffers tuples per key in priority queues and releases those at or below the
*low-watermark* — the minimum over all input channels of the maximum id/ts seen
(``maxs[]`` logic, ``:79-94``). The batch-level restatement:

- each input channel advances a watermark = max (ts or id) of the batches it has
  delivered;
- buffered batches are merged, stably sorted by (ts, id) (or (id,)), and the prefix
  with sort-key <= min(channel watermarks) is released; the rest is retained.

Modes mirror ``ordering_mode_t`` (``wf/basic.hpp:129``): ID, TS, TS_RENUMBERING
(released tuples are renumbered with a progressive id — used by DETERMINISTIC
count-based windows downstream, ``wf/pipegraph.hpp:1954-1957``).

The merge-sort-release kernel is jitted; the host side only tracks watermarks.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..basic import ordering_mode_t
from ..batch import Batch, CTRL_DTYPE, concat_batches


class Ordering_Node:
    def __init__(self, n_inputs: int, mode: ordering_mode_t = ordering_mode_t.TS):
        self.n_inputs = int(n_inputs)
        self.mode = mode
        self._wm = [None] * self.n_inputs        # per-channel high watermark
        self._pending: Optional[Batch] = None
        self._next_id = 0
        self._release_jit = jax.jit(self._release)

    # -- jitted core ------------------------------------------------------------------

    def _sort_key(self, b: Batch):
        return b.id if self.mode == ordering_mode_t.ID else b.ts

    def _release(self, pending: Batch, low_wm):
        k = self._sort_key(pending)
        big = jnp.iinfo(CTRL_DTYPE).max
        keyv = jnp.where(pending.valid, k, big)
        order = jnp.argsort(keyv, stable=True)
        sortedb = pending.select(order, jnp.ones_like(pending.valid))
        ks = jnp.where(sortedb.valid, self._sort_key(sortedb), big)
        releasable = ks <= low_wm
        out = sortedb.mask(releasable)
        kept = sortedb.mask(sortedb.valid & ~releasable)
        return out, kept

    # -- host protocol ----------------------------------------------------------------

    def push(self, channel: int, batch: Batch) -> Optional[Batch]:
        """Deliver a batch from ``channel``; returns a released (ordered) batch or
        None if nothing can be released yet."""
        import numpy as np
        k = np.asarray(self._sort_key(batch))
        v = np.asarray(batch.valid)
        if v.any():
            mx = int(k[v].max())
            self._wm[channel] = mx if self._wm[channel] is None else max(
                self._wm[channel], mx)
        self._pending = (batch if self._pending is None
                         else concat_batches(self._pending, batch))
        return self.try_release()

    def try_release(self) -> Optional[Batch]:
        """Release the prefix at or below the current low-watermark, if every
        channel has established one."""
        if self._pending is None or any(w is None for w in self._wm):
            return None
        low = min(self._wm)
        out, kept = self._release_jit(self._pending, jnp.asarray(low, CTRL_DTYPE))
        self._pending = kept
        return self._maybe_renumber(out)

    def close_channel(self, channel: int) -> Optional[Batch]:
        """Channel EOS: it no longer gates the low-watermark (the reference drops
        the channel from ``maxs[]`` when its EOS marker arrives). Returns any batch
        that the advanced watermark releases."""
        self._wm[channel] = int(jnp.iinfo(CTRL_DTYPE).max - 1)
        return self.try_release()

    def flush(self) -> Optional[Batch]:
        """EOS: release everything, sorted."""
        if self._pending is None:
            return None
        out, _ = self._release_jit(self._pending,
                                   jnp.asarray(jnp.iinfo(CTRL_DTYPE).max - 1, CTRL_DTYPE))
        self._pending = None
        return self._maybe_renumber(out)

    def _maybe_renumber(self, out: Optional[Batch]) -> Optional[Batch]:
        if out is None or self.mode != ordering_mode_t.TS_RENUMBERING:
            return out
        import numpy as np
        n = int(np.asarray(jnp.sum(out.valid)))
        ids = jnp.cumsum(out.valid.astype(CTRL_DTYPE)) - 1 + self._next_id
        self._next_id += n
        return out.replace(id=jnp.where(out.valid, ids, out.id))
