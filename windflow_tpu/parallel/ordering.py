"""Ordering — deterministic order restoration at merge/shuffle boundaries.

Counterpart of ``Ordering_Node`` (``wf/ordering_node.hpp:47-287``): the reference
buffers tuples per key in priority queues and releases those at or below the
*low-watermark* — the minimum over all input channels of the maximum id/ts seen
(``maxs[]`` logic, ``:79-94``). The batch-level restatement:

- each input channel advances a watermark = max (ts or id) of the batches it has
  delivered;
- buffered batches are merged, stably sorted by (ts, id) (or (id,)), and the
  provably-complete prefix is released, the rest retained. ID mode releases
  sort-key <= min(channel watermarks) like the reference (a channel's ids
  strictly increase, so watermark ties cannot recur); TS modes release strictly
  BELOW the low watermark — a channel may deliver more tuples EQUAL to its own
  watermark, and releasing those ties early would leak poll interleaving into
  the output order. Channel EOS lifts that channel's gate entirely.

Modes mirror ``ordering_mode_t`` (``wf/basic.hpp:129``): ID, TS, TS_RENUMBERING
(released tuples are renumbered with a progressive id — used by DETERMINISTIC
count-based windows downstream, ``wf/pipegraph.hpp:1954-1957``).

Hot-path cost (VERDICT r03 weak #4): watermarks live ON DEVICE (a jitted
``.at[channel].max`` update — no per-push device→host max fetch), the
low-watermark compare and TS_RENUMBERING progressive-id assignment are folded
into the jitted release, and the host reads back exactly ONE tiny transfer per
push — the packed ``[n_released, n_kept]`` counts, which also feed the backlog
trim and (via ``last_release_count``) the driver's chunker, so no second sync
follows.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..basic import ordering_mode_t
from ..batch import Batch, CTRL_DTYPE, concat_batches

#: "no watermark yet" sentinel — gates the low-watermark on device exactly like
#: the host-side ``None`` it replaces (a channel at the sentinel keeps
#: ``min(wm)`` at the sentinel, and the release predicate masks on that).
WM_NONE = jnp.iinfo(CTRL_DTYPE).min


class Ordering_Node:
    def __init__(self, n_inputs: int, mode: ordering_mode_t = ordering_mode_t.TS):
        self.n_inputs = int(n_inputs)
        self.mode = mode
        self._wm_dev = jnp.full((self.n_inputs,), WM_NONE, CTRL_DTYPE)
        self._pending: Optional[Batch] = None
        self._pending_chan = None                # i32[C] source channel per lane
        self._next_id = jnp.zeros((), CTRL_DTYPE)   # device scalar (renumbering)
        #: valid-lane count of the batch last returned by push/try_release —
        #: already fetched with the release counts, so drivers chunking the
        #: released batch need no second device sync
        self.last_release_count = 0
        self._release_jit = jax.jit(self._release, static_argnums=(3,))

        @jax.jit
        def _wm_update(wm, ch, k, valid):
            mx = jnp.max(jnp.where(valid, k, WM_NONE))
            return wm.at[ch].max(mx)
        self._wm_update = _wm_update

    # -- jitted core ------------------------------------------------------------------

    def _sort_keys(self, b: Batch, chan):
        """(primary, secondary, tertiary) composite sort: id/ts, then the other
        control field, then source channel — a TOTAL deterministic order even when
        two channels carry equal (ts, id) pairs (poll interleaving must not leak
        into release order)."""
        prim = b.id if self.mode == ordering_mode_t.ID else b.ts
        sec = b.ts if self.mode == ordering_mode_t.ID else b.id
        return prim, sec, chan

    def _release(self, pending: Batch, chan, wm, release_all=False):
        big = jnp.iinfo(CTRL_DTYPE).max
        prim, sec, tert = self._sort_keys(pending, chan)
        primv = jnp.where(pending.valid, prim, big)
        # jnp.lexsort: LAST key is the primary sort key
        order = jnp.lexsort((tert, sec, primv))
        sortedb = pending.select(order, jnp.ones_like(pending.valid))
        chan_s = jnp.take(chan, order)
        if release_all:
            # EOS: every valid lane goes, sorted. No watermark compare — a
            # valid sort-key equal to the dtype max is indistinguishable from
            # the invalid-lane sentinel in `ks`, so any threshold would either
            # drop it or resurrect dead lanes.
            out = sortedb
            kept = sortedb.mask(jnp.zeros_like(sortedb.valid))
        else:
            low_wm = jnp.min(wm)
            ks = jnp.where(sortedb.valid,
                           self._sort_keys(sortedb, chan_s)[0], big)
            # ID mode: a channel's ids strictly increase, so ties AT the
            # watermark cannot arrive again — release `<=` like the reference
            # (wf/ordering_node.hpp:197 `id > min_id` break). TS modes: a
            # channel may deliver MORE tuples equal to its own watermark, so
            # releasing ties at the low watermark would leak poll interleaving
            # into the output order (fuzz-caught); hold them until every
            # watermark strictly passes.
            if self.mode == ordering_mode_t.ID:
                releasable = ks <= low_wm
            else:
                releasable = ks < low_wm
            # a channel still at the WM_NONE sentinel gates everything — the
            # device-side restatement of the old host `any(w is None)` check
            releasable &= low_wm != WM_NONE
            out = sortedb.mask(releasable)
            kept = sortedb.mask(sortedb.valid & ~releasable)
        counts = jnp.stack([jnp.sum(out.valid.astype(CTRL_DTYPE)),
                            jnp.sum(kept.valid.astype(CTRL_DTYPE))])
        return out, kept, chan_s, counts

    # -- host protocol ----------------------------------------------------------------

    def push(self, channel: int, batch: Batch) -> Optional[Batch]:
        """Deliver a batch from ``channel``; returns a released (ordered) batch or
        None if nothing can be released yet. The watermark update runs on
        device — no host readback here."""
        k = batch.id if self.mode == ordering_mode_t.ID else batch.ts
        self._wm_dev = self._wm_update(self._wm_dev,
                                       jnp.asarray(channel, CTRL_DTYPE),
                                       k, batch.valid)
        chan = jnp.full((batch.capacity,), channel, CTRL_DTYPE)
        if self._pending is None:
            self._pending, self._pending_chan = batch, chan
        else:
            self._pending = concat_batches(self._pending, batch)
            self._pending_chan = jnp.concatenate([self._pending_chan, chan])
        return self.try_release()

    def _pad_pow2(self):
        """Pad the pending batch to a power-of-two capacity so ``_release_jit``
        sees O(log max-backlog) distinct shapes instead of one per concat."""
        b, chan = self._pending, self._pending_chan
        C = b.capacity
        P = 1
        while P < C:
            P *= 2
        if P == C:
            return
        pad = P - C

        def pz(a):
            return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        self._pending = Batch(key=pz(b.key), id=pz(b.id), ts=pz(b.ts),
                              payload=jax.tree.map(pz, b.payload),
                              valid=pz(b.valid))
        self._pending_chan = jnp.pad(chan, (0, pad))

    def _trim_pow2(self, n: int):
        """Compact the retained batch (live lanes first, stable) and trim its
        capacity to the power of two covering the live count ``n`` (already
        fetched with the release counts — no sync here) — without this the
        padded kept capacity compounds with every concat (exponential growth);
        with it, capacities stay pow2 and bounded by ~2x the held-back backlog."""
        b, chan = self._pending, self._pending_chan
        cap = 1
        while cap < max(n, 1):
            cap *= 2
        cap = max(cap, 64)
        if b.capacity <= cap:
            return
        order = jnp.argsort(~b.valid, stable=True)    # live lanes to the front
        sel = order[:cap]

        def take(a):
            return jnp.take(a, sel, axis=0)
        self._pending = Batch(key=take(b.key), id=take(b.id), ts=take(b.ts),
                              payload=jax.tree.map(take, b.payload),
                              valid=take(b.valid))
        self._pending_chan = jnp.take(chan, sel)

    def try_release(self) -> Optional[Batch]:
        """Release the prefix at or below the current low-watermark (the
        gating on channels without a watermark happens inside the jitted
        release via the WM_NONE sentinel). Exactly ONE host readback: the
        packed [n_released, n_kept] counts."""
        import numpy as np
        if self._pending is None:
            return None
        self._pad_pow2()
        out, kept, kept_chan, counts = self._release_jit(
            self._pending, self._pending_chan, self._wm_dev)
        self._pending, self._pending_chan = kept, kept_chan
        n_out, n_kept = (int(x) for x in np.asarray(counts))
        self._trim_pow2(n_kept)
        if n_out == 0:
            return None
        self.last_release_count = n_out
        return self._maybe_renumber(out)

    def close_channel(self, channel: int) -> Optional[Batch]:
        """Channel EOS: it no longer gates the low-watermark (a liveness
        extension over the reference, whose ``eosnotify`` only flushes once ALL
        channels have closed — see the note below). Returns any batch the
        advanced watermark releases. The sentinel is the full dtype max, which
        un-gates the channel for everything below the max; a valid tuple AT the
        dtype max rides out with ``flush`` (whose release is unconditional on
        valid lanes) — mid-stream it is indistinguishable from the invalid-lane
        sentinel, so no watermark can free it.

        Reference relationship: ``wf/ordering_node.hpp`` ``eosnotify`` holds
        everything until every channel has delivered EOS, then flushes; the
        per-channel un-gating here releases the surviving channels' tuples as
        soon as a dead channel can no longer reorder them — same final order,
        earlier liveness."""
        self._wm_dev = self._wm_dev.at[channel].set(jnp.iinfo(CTRL_DTYPE).max)
        return self.try_release()

    def flush(self) -> Optional[Batch]:
        """EOS: release everything, sorted."""
        import numpy as np
        if self._pending is None:
            return None
        self._pad_pow2()
        out, _, _, counts = self._release_jit(
            self._pending, self._pending_chan, self._wm_dev, True)
        self._pending, self._pending_chan = None, None
        self.last_release_count = int(np.asarray(counts)[0])
        return self._maybe_renumber(out)

    def _maybe_renumber(self, out: Optional[Batch]) -> Optional[Batch]:
        """Progressive-id assignment, fully on device (``_next_id`` is a device
        scalar carried across releases — no host readback)."""
        if out is None or self.mode != ordering_mode_t.TS_RENUMBERING:
            return out
        ids = jnp.cumsum(out.valid.astype(CTRL_DTYPE)) - 1 + self._next_id
        self._next_id = self._next_id + jnp.sum(out.valid.astype(CTRL_DTYPE))
        return out.replace(id=jnp.where(out.valid, ids, out.id))
