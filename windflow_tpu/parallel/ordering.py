"""Ordering — deterministic order restoration at merge/shuffle boundaries.

Counterpart of ``Ordering_Node`` (``wf/ordering_node.hpp:47-287``): the reference
buffers tuples per key in priority queues and releases those at or below the
*low-watermark* — the minimum over all input channels of the maximum id/ts seen
(``maxs[]`` logic, ``:79-94``). The batch-level restatement:

- each input channel advances a watermark = max (ts or id) of the batches it has
  delivered;
- buffered batches are merged, stably sorted by (ts, id) (or (id,)), and the
  provably-complete prefix is released, the rest retained. ID mode releases
  sort-key <= min(channel watermarks) like the reference (a channel's ids
  strictly increase, so watermark ties cannot recur); TS modes release strictly
  BELOW the low watermark — a channel may deliver more tuples EQUAL to its own
  watermark, and releasing those ties early would leak poll interleaving into
  the output order. Channel EOS lifts that channel's gate entirely.

Modes mirror ``ordering_mode_t`` (``wf/basic.hpp:129``): ID, TS, TS_RENUMBERING
(released tuples are renumbered with a progressive id — used by DETERMINISTIC
count-based windows downstream, ``wf/pipegraph.hpp:1954-1957``).

The merge-sort-release kernel is jitted; the host side only tracks watermarks.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..basic import ordering_mode_t
from ..batch import Batch, CTRL_DTYPE, concat_batches


class Ordering_Node:
    def __init__(self, n_inputs: int, mode: ordering_mode_t = ordering_mode_t.TS):
        self.n_inputs = int(n_inputs)
        self.mode = mode
        self._wm = [None] * self.n_inputs        # per-channel high watermark
        self._pending: Optional[Batch] = None
        self._pending_chan = None                # i32[C] source channel per lane
        self._next_id = 0
        self._release_jit = jax.jit(self._release, static_argnums=(3,))

    # -- jitted core ------------------------------------------------------------------

    def _sort_keys(self, b: Batch, chan):
        """(primary, secondary, tertiary) composite sort: id/ts, then the other
        control field, then source channel — a TOTAL deterministic order even when
        two channels carry equal (ts, id) pairs (poll interleaving must not leak
        into release order)."""
        prim = b.id if self.mode == ordering_mode_t.ID else b.ts
        sec = b.ts if self.mode == ordering_mode_t.ID else b.id
        return prim, sec, chan

    def _release(self, pending: Batch, chan, low_wm, release_all=False):
        big = jnp.iinfo(CTRL_DTYPE).max
        prim, sec, tert = self._sort_keys(pending, chan)
        primv = jnp.where(pending.valid, prim, big)
        # jnp.lexsort: LAST key is the primary sort key
        order = jnp.lexsort((tert, sec, primv))
        sortedb = pending.select(order, jnp.ones_like(pending.valid))
        chan_s = jnp.take(chan, order)
        if release_all:
            # EOS: every valid lane goes, sorted. No watermark compare — a
            # valid sort-key equal to the dtype max is indistinguishable from
            # the invalid-lane sentinel in `ks`, so any threshold would either
            # drop it or resurrect dead lanes.
            out = sortedb
            kept = sortedb.mask(jnp.zeros_like(sortedb.valid))
            return out, kept, chan_s
        ks = jnp.where(sortedb.valid,
                       self._sort_keys(sortedb, chan_s)[0], big)
        # ID mode: a channel's ids strictly increase, so ties AT the watermark
        # cannot arrive again — release `<=` like the reference
        # (wf/ordering_node.hpp:197 `id > min_id` break). TS modes: a channel
        # may deliver MORE tuples equal to its own watermark, so releasing ties
        # at the low watermark would leak poll interleaving into the output
        # order (fuzz-caught); hold them until every watermark strictly passes.
        if self.mode == ordering_mode_t.ID:
            releasable = ks <= low_wm
        else:
            releasable = ks < low_wm
        out = sortedb.mask(releasable)
        kept = sortedb.mask(sortedb.valid & ~releasable)
        return out, kept, chan_s

    # -- host protocol ----------------------------------------------------------------

    def push(self, channel: int, batch: Batch) -> Optional[Batch]:
        """Deliver a batch from ``channel``; returns a released (ordered) batch or
        None if nothing can be released yet."""
        import numpy as np
        k = np.asarray(batch.id if self.mode == ordering_mode_t.ID else batch.ts)
        v = np.asarray(batch.valid)
        if v.any():
            mx = int(k[v].max())
            self._wm[channel] = mx if self._wm[channel] is None else max(
                self._wm[channel], mx)
        chan = jnp.full((batch.capacity,), channel, CTRL_DTYPE)
        if self._pending is None:
            self._pending, self._pending_chan = batch, chan
        else:
            self._pending = concat_batches(self._pending, batch)
            self._pending_chan = jnp.concatenate([self._pending_chan, chan])
        return self.try_release()

    def _pad_pow2(self):
        """Pad the pending batch to a power-of-two capacity so ``_release_jit``
        sees O(log max-backlog) distinct shapes instead of one per concat."""
        b, chan = self._pending, self._pending_chan
        C = b.capacity
        P = 1
        while P < C:
            P *= 2
        if P == C:
            return
        pad = P - C

        def pz(a):
            return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        self._pending = Batch(key=pz(b.key), id=pz(b.id), ts=pz(b.ts),
                              payload=jax.tree.map(pz, b.payload),
                              valid=pz(b.valid))
        self._pending_chan = jnp.pad(chan, (0, pad))

    def _trim_pow2(self):
        """Compact the retained batch (live lanes first, stable) and trim its
        capacity to the power of two covering the live count — without this the
        padded kept capacity compounds with every concat (exponential growth);
        with it, capacities stay pow2 and bounded by ~2x the held-back backlog."""
        b, chan = self._pending, self._pending_chan
        import numpy as np
        n = int(np.asarray(jnp.sum(b.valid)))
        cap = 1
        while cap < max(n, 1):
            cap *= 2
        cap = max(cap, 64)
        if b.capacity <= cap:
            return
        order = jnp.argsort(~b.valid, stable=True)    # live lanes to the front
        sel = order[:cap]

        def take(a):
            return jnp.take(a, sel, axis=0)
        self._pending = Batch(key=take(b.key), id=take(b.id), ts=take(b.ts),
                              payload=jax.tree.map(take, b.payload),
                              valid=take(b.valid))
        self._pending_chan = jnp.take(chan, sel)

    def try_release(self) -> Optional[Batch]:
        """Release the prefix at or below the current low-watermark, if every
        channel has established one."""
        if self._pending is None or any(w is None for w in self._wm):
            return None
        self._pad_pow2()
        low = min(self._wm)
        out, kept, kept_chan = self._release_jit(
            self._pending, self._pending_chan, jnp.asarray(low, CTRL_DTYPE))
        self._pending, self._pending_chan = kept, kept_chan
        self._trim_pow2()
        return self._maybe_renumber(out)

    def close_channel(self, channel: int) -> Optional[Batch]:
        """Channel EOS: it no longer gates the low-watermark (the reference drops
        the channel from ``maxs[]`` when its EOS marker arrives). Returns any batch
        that the advanced watermark releases. The sentinel is the full dtype
        max, which un-gates the channel for everything below the max; a valid
        tuple AT the dtype max rides out with ``flush`` (whose release is
        unconditional on valid lanes) — mid-stream it is indistinguishable
        from the invalid-lane sentinel, so no watermark can free it."""
        self._wm[channel] = int(jnp.iinfo(CTRL_DTYPE).max)
        return self.try_release()

    def flush(self) -> Optional[Batch]:
        """EOS: release everything, sorted."""
        if self._pending is None:
            return None
        self._pad_pow2()
        out, _, _ = self._release_jit(
            self._pending, self._pending_chan,
            jnp.asarray(jnp.iinfo(CTRL_DTYPE).max, CTRL_DTYPE), True)
        self._pending, self._pending_chan = None, None
        return self._maybe_renumber(out)

    def _maybe_renumber(self, out: Optional[Batch]) -> Optional[Batch]:
        if out is None or self.mode != ordering_mode_t.TS_RENUMBERING:
            return out
        import numpy as np
        n = int(np.asarray(jnp.sum(out.valid)))
        ids = jnp.cumsum(out.valid.astype(CTRL_DTYPE)) - 1 + self._next_id
        self._next_id += n
        return out.replace(id=jnp.where(out.valid, ids, out.id))
