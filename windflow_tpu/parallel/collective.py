"""Explicit-collective multi-chip patterns over ICI: shard_map formulations of the
reference's cross-replica exchanges.

The GSPMD path (``parallel/sharding.py``) lets XLA infer collectives from sharding
annotations; this module is the hand-written counterpart for the three exchanges whose
communication pattern IS the algorithm — the cases where the reference dedicates a
custom emitter/topology:

- :func:`wmr_map_reduce` — Win_MapReduce with the MAP partition axis sharded over
  devices and the REDUCE combine as an ICI all-reduce (``psum``-style tree combine).
  Reference: WinMap_Emitter round-robin partitioning + REDUCE stage
  (``wf/win_mapreduce.hpp:180-230``, ``wf/wm_nodes.hpp:45-181``). Use when one
  window's content is too large for one chip.
- :func:`ring_pane_windows` — sliding windows over a pane-partial axis sharded in
  contiguous blocks, with boundary panes rotated from ring neighbours via
  ``ppermute`` (the ring-attention communication shape applied to Pane_Farm: each
  device combines local pane partials, pulls the (win_panes-1) successor panes it is
  missing from the next device(s) around the ring, never materializing the full pane
  axis anywhere). Reference: PLQ/WLQ pane sharing (``wf/pane_farm.hpp:175-213``) —
  single-process there, cross-chip here.
- :func:`keyed_all_to_all` — redistribute a batch so every tuple lands on the device
  that owns its key: per-destination compaction + ``lax.all_to_all``. This is the
  KF_Emitter / Standard_EmitterGPU ``create_sub_batch`` exchange
  (``wf/kf_nodes.hpp:74-90``, ``wf/standard_nodes_gpu.hpp:52-238``) carried over
  chip boundaries instead of thread queues.

All functions take an explicit mesh-axis name and run inside
``jax.shard_map``; static shapes throughout (fixed per-destination capacity +
validity masks — the batch discipline of the whole framework).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.segment import segment_rank

try:                                     # jax >= 0.4.35
    from jax import shard_map as _shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

#: the replication-check kwarg was renamed check_rep -> check_vma across jax versions
_CHECK_KW = ("check_vma" if "check_vma" in _inspect.signature(_shard_map).parameters
             else "check_rep")


def _axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


# -- Win_MapReduce over ICI ------------------------------------------------------------

def wmr_map_reduce(map_fn: Callable, combine: Callable, mesh: Mesh, *,
                   axis: str = "part"):
    """Build ``f(data, valid) -> result`` where ``data`` is one window's content
    [L, ...] sharded over ``axis`` in ``map_parallelism = mesh.shape[axis]``
    partitions. Each device runs ``map_fn(partition, valid)`` on its local slice
    (the reference MAP stage, role MAP), then the partials are tree-combined across
    the axis with an all-reduce built from ``combine`` (the REDUCE stage; for
    ``combine=jnp.add`` this is exactly ``lax.psum`` over ICI).

    ``map_fn``: (local_data [L/p, ...], local_valid [L/p]) -> partial (any pytree of
    arrays with matching shapes across devices). ``combine``: (partial, partial) ->
    partial, associative."""
    p = _axis_size(mesh, axis)
    known = combine in (jnp.add, jnp.maximum, jnp.minimum)
    reducer = {jnp.add: jax.lax.psum, jnp.maximum: jax.lax.pmax,
               jnp.minimum: jax.lax.pmin}.get(combine)

    def local(data, valid):
        partial = map_fn(data, valid)
        if known:
            return jax.tree.map(lambda x: reducer(x, axis), partial)
        # generic associative combine: all_gather + order-preserving tree fold.
        # The fold runs at the PYTREE level (combine sees whole partials, strictly
        # pairwise via vmap), pairing adjacent elements so non-commutative combines
        # see partials in axis order.
        g = jax.tree.map(lambda x: jax.lax.all_gather(x, axis), partial)
        n = p
        while n > 1:
            m = n // 2
            a = jax.tree.map(lambda x: x[0:2 * m:2], g)
            b = jax.tree.map(lambda x: x[1:2 * m:2], g)
            paired = jax.vmap(combine)(a, b)
            if n > 2 * m:
                rest = jax.tree.map(lambda x: x[2 * m:n], g)
                g = jax.tree.map(lambda pr, r: jnp.concatenate([pr, r], axis=0),
                                 paired, rest)
            else:
                g = paired
            n = m + (n - 2 * m)
        return jax.tree.map(lambda x: x[0], g)

    # the folded all_gather of the generic path is replicated by construction, but
    # the static varying-axes checker can't prove it — disable the check there
    return _shard_map(local, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=P(), **{_CHECK_KW: known})


# -- ring pane exchange ----------------------------------------------------------------

def ring_pane_windows(combine: Callable, identity, mesh: Mesh, *,
                      win_panes: int, slide_panes: int, axis: str = "win"):
    """Build ``f(panes [Ptot], pane_valid [Ptot]) -> (win_results, win_valid)`` for
    sliding windows of ``win_panes`` pane partials sliding by ``slide_panes``, with
    the pane axis sharded in contiguous blocks over ``axis``.

    Each device owns panes [d*B, (d+1)*B). A window starting in block d can extend
    ``win_panes - 1`` panes into successor blocks, so the ring rotates each block to
    its left neighbour ``ceil((win_panes-1)/B)`` times via ``ppermute``; the device
    appends the halo and computes its windows locally — O(halo) bytes over ICI per
    step, full pane axis never gathered. Window starts are global multiples of
    ``slide_panes``; each window is emitted by the device whose block contains its
    start pane — the WF_Emitter ownership rule applied to a sharded pane axis, and
    the emitted window set is identical to the single-device computation regardless
    of the device count.

    Only windows fully covered by panes present on the ring are valid (trailing
    windows whose halo would wrap past the end of the pane axis are masked, and the
    wrap-around halo from device 0 is marked invalid)."""
    p = _axis_size(mesh, axis)

    def local(panes, pane_valid):
        B = panes.shape[0]
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i - 1) % p) for i in range(p)]     # send left = pull from right
        # per-step halo widths: step s ships the first min(B, remaining) panes of
        # block idx+s+1 — only the panes windows can actually read, so ICI traffic
        # is O(win_panes) total, not O(B) per step
        widths, rem = [], max(win_panes - 1, 0)
        while rem > 0:
            widths.append(min(B, rem))
            rem -= widths[-1]
        ext, ext_valid = panes, pane_valid
        buf, buf_valid = panes, pane_valid
        for s, w in enumerate(widths):                  # widths are non-increasing
            buf = jax.lax.ppermute(buf[:w], axis, perm)
            buf_valid = jax.lax.ppermute(buf_valid[:w], axis, perm)
            # buffer received on step s holds the leading panes of block idx+s+1:
            # wrapped past the end of the pane axis if idx+s+1 >= p — mask off
            wrapped = idx + s + 1 >= p
            ext = jnp.concatenate([ext, buf], axis=0)
            ext_valid = jnp.concatenate(
                [ext_valid, jnp.where(wrapped, False, buf_valid)], axis=0)
        # windows start at GLOBAL pane indices that are multiples of slide_panes;
        # this device owns the ones falling inside its block [idx*B, (idx+1)*B).
        # First owned start as a local offset (0..slide-1), then every slide after
        # it; nwin is the worst-case count, extras masked by (start < B).
        base = idx.astype(jnp.int32) * B
        off = (-base) % slide_panes
        nwin = (B + slide_panes - 1) // slide_panes
        starts = off + jnp.arange(nwin, dtype=jnp.int32) * slide_panes

        def one(start):
            sl = jax.lax.dynamic_slice_in_dim(ext, start, win_panes, axis=0)
            vl = jax.lax.dynamic_slice_in_dim(ext_valid, start, win_panes, axis=0)
            masked = jnp.where(vl.reshape(vl.shape + (1,) * (sl.ndim - 1)),
                               sl, identity)
            res = masked[0]
            for i in range(1, win_panes):
                res = combine(res, masked[i])
            return res, jnp.all(vl) & (start < B)
        res, valid = jax.vmap(one)(starts)
        return res, valid

    return _shard_map(local, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=(P(axis), P(axis)))


# -- keyed all-to-all ------------------------------------------------------------------

def keyed_all_to_all(mesh: Mesh, *, axis: str = "key", capacity: int | None = None,
                     return_residue: bool = False):
    """Build ``f(keys [C], valid [C], payload pytree of [C, ...]) ->
    (keys, valid, payload, n_left_behind)`` redistributing every live row to the
    device that owns its key (owner = key % n_devices), over one ``lax.all_to_all``.

    Per (source, destination) lane budget is ``capacity`` rows (default C // p);
    each source compacts its rows per destination into [p, capacity] sub-batches
    (the ``create_sub_batch`` compaction of ``wf/standard_nodes_gpu.hpp``, done with
    a rank-within-destination scatter), exchanges, and flattens back to a [p*cap]
    local batch with a validity mask.

    **Nothing is silently lost.** Rows beyond a lane budget stay on their source and
    are reported in ``n_left_behind`` — a per-source [p] i32 count (all zeros ⇒ the
    exchange was complete; with ``capacity = C`` overflow is impossible). With
    ``return_residue=True`` the per-row residue mask [global C] is also returned so
    the caller can re-run the exchange on exactly the rows left behind —
    :func:`keyed_all_to_all_lossless` wraps that into the multi-round blocking
    discipline of the reference's bounded queues (``FF_BOUNDED_BUFFER`` blocks; it
    never drops)."""
    p = _axis_size(mesh, axis)

    def local(keys, valid, payload):
        C = keys.shape[0]
        cap = capacity if capacity is not None else C // p
        if cap < 1:
            raise ValueError(
                f"keyed_all_to_all: per-(src,dst) lane capacity resolved to "
                f"{cap} (local rows {C}, devices {p}) — no row could ever be "
                f"delivered and the lossless wrapper would loop forever; pass "
                f"an explicit capacity >= 1")
        dest = jnp.where(valid, keys % p, p)            # p = parked lane
        # rank of each row among live rows with the same destination (stream order)
        rank = segment_rank(dest, valid)
        # scatter rows into [p, cap] slots per destination
        slot_ok = valid & (rank < cap)
        flat_slot = jnp.where(slot_ok, dest * cap + rank, p * cap)

        def place(arr, fill=0):
            out = jnp.full((p * cap + 1,) + arr.shape[1:], fill, arr.dtype)
            out = out.at[flat_slot].set(arr)
            return out[:p * cap].reshape((p, cap) + arr.shape[1:])

        sub_keys = place(keys)
        sub_valid = place(slot_ok.astype(jnp.int32)).astype(jnp.bool_)
        sub_pay = jax.tree.map(place, payload)
        # exchange: axis 0 is the destination axis
        ex = lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                          tiled=False)
        rk, rv = ex(sub_keys), ex(sub_valid)
        rp = jax.tree.map(ex, sub_pay)
        flat = lambda a: a.reshape((p * cap,) + a.shape[2:])
        residue = valid & ~slot_ok                       # live rows left behind
        n_left = jnp.sum(residue.astype(jnp.int32)).reshape(1)
        out = (flat(rk), flat(rv), jax.tree.map(flat, rp), n_left)
        return out + (residue,) if return_residue else out

    specs = (P(axis), P(axis), P(axis), P(axis))
    if return_residue:
        specs = specs + (P(axis),)
    return _shard_map(local, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
                      out_specs=specs)


def keyed_all_to_all_lossless(mesh: Mesh, *, axis: str = "key",
                              capacity: int | None = None):
    """Multi-round :func:`keyed_all_to_all` that delivers EVERY live row: rounds of
    exchange run until no source reports rows left behind, and each receiver's
    rounds are concatenated along the batch axis. The host loop is the blocking
    backpressure of the reference's bounded queues — later rounds are the emitter
    thread blocking on a full ``FF_BOUNDED_BUFFER`` until the consumer drains it.
    The round count is identical on every process (it is driven by the summed
    left-behind counts, which all processes compute), so the loop is safe under
    multi-controller execution. Returns ``(keys, valid, payload, n_rounds)``.

    Memory note: receiver rounds are concatenated along the batch axis, so the
    output capacity is ``n_rounds * p * cap`` and the concatenate may leave the
    result partially replicated depending on XLA's layout choice — size
    ``capacity`` so the common case is one round, and treat multi-round as the
    backpressure slow path (exactly like a blocking queue under overload)."""
    ex = jax.jit(keyed_all_to_all(mesh, axis=axis, capacity=capacity,
                                  return_residue=True))

    def run(keys, valid, payload):
        outs = []
        v = valid
        while True:
            rk, rv, rp, n_left, resid = ex(keys, v, payload)
            outs.append((rk, rv, rp))
            if int(jnp.sum(n_left)) == 0:
                break
            v = resid
        cat = lambda parts: jnp.concatenate(parts, axis=0)
        ks = cat([o[0] for o in outs])
        vs = cat([o[1] for o in outs])
        ps = jax.tree.map(lambda *ls: cat(list(ls)), *[o[2] for o in outs])
        return ks, vs, ps, len(outs)

    return run
