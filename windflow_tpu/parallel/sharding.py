"""Sharded execution: place a compiled chain's state and batches over a device mesh.

The reference's parallelism knobs (operator ``parallelism`` replicas, KF/WF emitters)
become sharding rules (SURVEY §2.6): the batch capacity axis shards over ``dp``; keyed
state tables ([K, ...]) shard their key axis; window engines shard their archive by
key. XLA/GSPMD inserts the collectives (the scatter/gather across shards that the
reference performs with ``ff_send_out_to`` queue hops) over ICI.

Usage::

    mesh = make_mesh(8)
    sharded = ShardedChain(chain, mesh)     # re-places state, shards pushes
    out = sharded.push(batch)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch
from ..runtime.pipeline import CompiledChain


def _state_sharding(op, state, mesh: Mesh, axis: str):
    """Shard rule for one operator's state pytree: keyed tables shard the leading
    (key) axis; scalars/small states replicate."""
    shard_axis = getattr(op, "shard_axis", "key")
    num_keys = getattr(op, "num_keys", None)

    def place(leaf):
        if (shard_axis in ("key", "window") and num_keys is not None
                and getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] == num_keys and num_keys % mesh.devices.size == 0):
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())
    return jax.tree.map(place, state)


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_batch(batch: Batch, mesh: Mesh, axis: str = "dp") -> Batch:
    s = batch_sharding(mesh, axis)
    return jax.tree.map(lambda a: jax.device_put(a, s), batch)


class ShardedChain:
    """Wraps a :class:`CompiledChain`, placing its states on the mesh so every
    ``push``/``flush`` runs as one GSPMD-partitioned program."""

    def __init__(self, chain: CompiledChain, mesh: Mesh, axis: str = "dp"):
        self.chain = chain
        self.mesh = mesh
        self.axis = axis
        chain.states = [
            jax.device_put(st, _state_sharding(op, st, mesh, axis)) if st is not None
            else None
            for op, st in zip(chain.ops, chain.states)]

    def push(self, batch: Batch) -> Batch:
        return self.chain.push(shard_batch(batch, self.mesh, self.axis))

    def flush(self):
        return self.chain.flush()

    def result(self):
        return self.chain.result()
