"""Sharded execution: place a compiled chain's state and batches over a device mesh.

The reference's parallelism knobs (operator ``parallelism`` replicas, KF/WF emitters)
become sharding rules (SURVEY §2.6): the batch capacity axis shards over ``dp``; keyed
state tables ([K, ...]) shard their key axis; window engines shard their archive by
key. XLA/GSPMD inserts the collectives (the scatter/gather across shards that the
reference performs with ``ff_send_out_to`` queue hops) over ICI.

Usage::

    mesh = make_mesh(8)
    sharded = ShardedChain(chain, mesh)     # re-places state, shards pushes
    out = sharded.push(batch)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch
from ..runtime.pipeline import CompiledChain


def _state_sharding(op, state, mesh: Mesh, axis: str,
                    window_key_axis: Optional[str] = None):
    """Shard rule for one operator's state pytree, dispatched on the op's declared
    ``shard_axis``:

    - ``"key"`` (Key_Farm/Key_FFAT): leaves whose leading dim is the op's key-table
      size shard their key axis (KF_Emitter whole-key routing as a placement rule);
      everything else replicates.
    - ``"window"`` (Win_Farm): the fired-window [W] axis partitions *inside* the
      program via the ``with_sharding_constraint`` set by
      :meth:`Win_Seq.set_window_sharding`. The archive rings REPLICATE by default
      (every chip sees every tuple — the WF_Emitter multicast,
      ``wf/wf_nodes.hpp:182-204``, as a sharding rule); with an explicit
      ``window_key_axis`` (2-D key x win layouts) a KEYED farm's [K, ...] archive
      shards its key axis instead — the reference distributes a keyed Win_Farm's
      tuples by ``hash(key) % pardegree`` before the window round-robin
      (``wf/wf_nodes.hpp:157-204``), so at large K full replication wastes HBM.
    """
    shard_axis = getattr(op, "shard_axis", "key")
    num_keys = getattr(op, "num_keys", None)
    if shard_axis == "window":
        key_ax = window_key_axis

        def place_win(leaf):
            if (key_ax is not None and num_keys is not None and num_keys > 1
                    and getattr(leaf, "ndim", 0) >= 1
                    and leaf.shape[0] == num_keys
                    and num_keys % mesh.shape[key_ax] == 0):
                return NamedSharding(mesh, P(key_ax))
            return NamedSharding(mesh, P())
        return jax.tree.map(place_win, state)

    def place(leaf):
        if (shard_axis == "key" and num_keys is not None
                and getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] == num_keys
                and num_keys % mesh.shape.get(axis, mesh.devices.size) == 0):
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())
    return jax.tree.map(place, state)


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_batch(batch: Batch, mesh: Mesh, axis: str = "dp") -> Batch:
    s = batch_sharding(mesh, axis)
    return jax.tree.map(lambda a: jax.device_put(a, s), batch)


# ----------------------------------------------------------- key ownership

@dataclasses.dataclass(frozen=True)
class ShardAssignment:
    """Deterministic key-slot -> shard ownership map of the shard-local
    supervision layer (``runtime/supervisor.py`` ``ShardedSupervisor``).

    Base rule: ``owner(key) = key % num_shards`` — the reference's
    ``hash(key) % pardegree`` KF_Emitter routing (``wf/standard_emitter.hpp``)
    applied at the supervision boundary (key slots are already hashed at
    ingest by ``batch.hash_key_to_slot``). ``moves`` is a small tuple of
    ``(key_slot, shard)`` overrides — the governor-driven re-sharding plan's
    targeted key moves. Doubling ``num_shards`` splits every shard in two
    (``key % 2N ≡ key % N (mod N)``), so a ``4 -> 8`` reshard never shuffles
    keys between surviving pairs.

    Pure data + a cached jitted splitter: the assignment is JSON-serializable
    (``to_meta``/``from_meta``) so checkpoints record the layout epoch and a
    supervised replay re-derives IDENTICAL shard assignments."""

    num_shards: int
    moves: Tuple[Tuple[int, int], ...] = ()

    def __post_init__(self):
        if int(self.num_shards) < 1:
            raise ValueError(f"ShardAssignment: num_shards must be >= 1, "
                             f"got {self.num_shards}")
        norm = tuple(sorted((int(k), int(s)) for k, s in self.moves))
        if len({k for k, _s in norm}) != len(norm):
            # the host-side owner() (first match) and the traced owner_of()
            # (last jnp.where) would disagree on the duplicate's owner —
            # reshard planning would then rebuild the wrong shard
            dupes = sorted({k for k, _s in norm
                            if sum(1 for kk, _ in norm if kk == k) > 1})
            raise ValueError(
                f"ShardAssignment: key slot(s) {dupes} appear in more than "
                f"one move — each key has exactly one owner")
        for k, s in norm:
            if not (0 <= s < self.num_shards):
                raise ValueError(
                    f"ShardAssignment: move {k} -> shard {s} references a "
                    f"nonexistent shard (have {self.num_shards})")
        object.__setattr__(self, "num_shards", int(self.num_shards))
        object.__setattr__(self, "moves", norm)

    # -- ownership ---------------------------------------------------------

    def owner_of(self, keys):
        """Owning shard per key slot (array in, array out; works traced)."""
        own = keys % jnp.asarray(self.num_shards, keys.dtype)
        for k, s in self.moves:
            own = jnp.where(keys == k, jnp.asarray(s, own.dtype), own)
        return own

    def owner(self, key_slot: int) -> int:
        """Host-side single-key owner (reshard planning / tests)."""
        for k, s in self.moves:
            if k == int(key_slot):
                return s
        return int(key_slot) % self.num_shards

    # -- the splitter (reshard_pack: the perf-gate-pinned program) ---------

    def split_fn(self):
        """ONE jitted program mapping a batch to its ``num_shards`` masked
        sub-batches: lane content is preserved verbatim, each sub-batch's
        ``valid`` is intersected with key ownership — so the union of live
        lanes over all shards is exactly the input's live lanes (no key
        dropped, no key duplicated). Cached per assignment; jax.jit caches
        one executable per batch shape — one host dispatch per input batch
        regardless of shard count. (The ``batch.key``-owned form; see
        :func:`make_splitter` for derived ownership keys.)"""
        fn = getattr(self, "_split_jit", None)
        if fn is None:
            fn = make_splitter(self)
            object.__setattr__(self, "_split_jit", fn)
        return fn

    def split(self, batch: Batch):
        """``[sub_batch_0, ..., sub_batch_{N-1}]`` for one input batch."""
        if self.num_shards == 1:
            return [batch]
        return list(self.split_fn()(batch))

    # -- serialization (checkpoint layout epoch) ---------------------------

    def to_meta(self) -> dict:
        return {"num_shards": self.num_shards,
                "moves": [[k, s] for k, s in self.moves]}

    @classmethod
    def from_meta(cls, meta: dict) -> "ShardAssignment":
        return cls(int(meta["num_shards"]),
                   tuple((int(k), int(s)) for k, s in meta.get("moves", ())))


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """A deterministic live re-sharding request, applied by the sharded
    supervisors at the first checkpoint barrier at-or-after ``at_pos``
    (barrier alignment is what makes replay re-derive the identical layout:
    the plan's effect is a pure function of committed stream position).

    ``new_shards``: the target shard count (None keeps the current count);
    ``moves``: targeted ``(key_slot, shard)`` overrides applied on top —
    the governor's hot-key rebalancing. Parsed from ``WF_RESHARD``
    (``"8"`` = double/grow to 8 at the next barrier, or full JSON
    ``{"at_pos": 64, "new_shards": 8, "moves": [[3, 1]]}``)."""

    new_shards: Optional[int] = None
    moves: Tuple[Tuple[int, int], ...] = ()
    at_pos: int = 0

    def __post_init__(self):
        object.__setattr__(self, "moves",
                           tuple((int(k), int(s)) for k, s in self.moves))
        if self.new_shards is not None:
            object.__setattr__(self, "new_shards", int(self.new_shards))
        object.__setattr__(self, "at_pos", int(self.at_pos))

    def apply_to(self, cur: ShardAssignment) -> ShardAssignment:
        """The new layout (validates move targets via ShardAssignment)."""
        n = self.new_shards if self.new_shards is not None else cur.num_shards
        # carry surviving targeted moves forward only when the shard count is
        # unchanged — a count change re-bases every key to key % N (the
        # deterministic split rule), and stale overrides would pin moved keys
        # to the OLD layout's hot-spot decisions
        base = cur.moves if n == cur.num_shards else ()
        merged = dict(base)
        merged.update(dict(self.moves))
        return ShardAssignment(n, tuple(merged.items()))

    @classmethod
    def resolve(cls, arg) -> Optional["ReshardPlan"]:
        """Normalize a driver's ``reshard=`` argument: None consults
        ``WF_RESHARD``; False forces off; "auto" passes through as the
        governor-driven sentinel (the caller handles it); a plan/dict/int/
        JSON string parses."""
        if arg is False:
            return None
        if isinstance(arg, cls):
            return arg
        if arg is None:
            import os
            raw = os.environ.get("WF_RESHARD", "").strip()
            if not raw:
                return None
            arg = raw
        if isinstance(arg, str):
            if arg == "auto":
                return "auto"  # type: ignore[return-value]
            import json
            arg = json.loads(arg) if arg[:1] in "[{" else int(arg)
        if isinstance(arg, int):
            return cls(new_shards=arg)
        if isinstance(arg, dict):
            return cls(new_shards=arg.get("new_shards"),
                       moves=tuple((int(k), int(s))
                                   for k, s in arg.get("moves", ())),
                       at_pos=arg.get("at_pos", 0))
        raise TypeError(f"reshard= accepts a ReshardPlan, dict, int, JSON "
                        f"string, 'auto', or None/False — got {type(arg)}")


def make_splitter(assignment: ShardAssignment, key_fn=None):
    """Jitted ``batch -> (sub_batch_0, ..., sub_batch_{N-1})`` splitter.

    ``key_fn`` (``TupleRef -> int`` key, the KeyBy convention) overrides the
    batch's ``key`` control field as the OWNERSHIP key. It is required
    whenever the stateful operators group on a derived key — a ``KeyBy``
    downstream, or an operator ``key_fn`` over a payload field that differs
    from the ingest key: ownership must follow the key the state tables
    use, or one group's tuples would scatter across shards and every shard
    would hold a partial (wrong) per-key state. The validator's WF115 flags
    the detectable case (a KeyBy under sharded supervision without a
    ``shard_key=``)."""
    n = assignment.num_shards

    def split(batch: Batch):
        if key_fn is None:
            keys = batch.key
        else:
            from ..batch import tuple_refs
            keys = jnp.asarray(jax.vmap(key_fn)(tuple_refs(batch)),
                               batch.key.dtype)
        own = assignment.owner_of(keys)
        return tuple(batch.replace(valid=batch.valid & (own == s))
                     for s in range(n))
    return jax.jit(split)


def affected_shards(old: ShardAssignment, new: ShardAssignment) -> set:
    """New-layout shard indices whose key set changes between ``old`` and
    ``new`` — the shards the re-sharding handoff must rebuild (the rest
    adopt their state untouched). A shard-count change affects every shard
    (``key % N`` re-bases all ranges — though a doubling only ever SPLITS
    each old shard, it still changes every new index's key set); with the
    count unchanged only the donor/recipient shards of the targeted moves
    are affected."""
    if old.num_shards != new.num_shards:
        return set(range(new.num_shards))
    out = set()
    for k in ({k for k, _ in old.moves} | {k for k, _ in new.moves}):
        a, b = old.owner(k), new.owner(k)
        if a != b:
            out.add(a)
            out.add(b)
    return out


def resolve_shards(arg) -> int:
    """Normalize a driver's ``shards=`` argument: None consults ``WF_SHARDS``
    (unset/empty/0/1 all mean OFF — the single-supervision-domain path,
    byte-for-byte today's code); an int passes through (0 = off, the env
    convention; negative is an error)."""
    if arg is None:
        import os
        raw = os.environ.get("WF_SHARDS", "").strip()
        arg = int(raw) if raw else 1
    n = int(arg)
    if n == 0:
        return 1                          # '0' means off, per ENV_FLAGS.md
    if n < 0:
        raise ValueError(f"shards= must be >= 0, got {n}")
    return n


class ShardedChain:
    """Wraps a :class:`CompiledChain`, placing its states on the mesh so every
    ``push``/``flush`` runs as one GSPMD-partitioned program.

    On a 1-D mesh, ``axis`` carries both the batch capacity axis and the state
    shard axis. On a 2-D mesh (``make_mesh_2d``), pass ``key_axis`` (and/or
    ``win_axis``) to place key tables / fired-window rows on a different mesh
    axis than the batch: batch over ``dp`` (operator replication), key state
    over ``key`` (KF whole-key routing), window rows over ``win`` (WF window
    ownership) — the dp x ep / dp x sp layouts of the scaling playbook.

    A KEYED window farm on a ``key x win`` mesh gets BOTH: its [K, ...] archive
    shards over ``key_axis`` (explicit key_axis only — on a 1-D mesh the
    archive stays replicated, the WF-multicast rule) while its fired-window [W]
    rows shard over ``win_axis``."""

    def __init__(self, chain: CompiledChain, mesh: Mesh, axis: str = "dp",
                 win_axis: Optional[str] = None, key_axis: Optional[str] = None):
        # validate axis names up front: a typo would otherwise surface as a bare
        # KeyError from inside jax.tree.map during device_put
        for name, val in (("axis", axis), ("win_axis", win_axis),
                          ("key_axis", key_axis)):
            if val is not None and val not in mesh.axis_names:
                raise ValueError(
                    f"ShardedChain: {name}={val!r} is not an axis of the mesh "
                    f"(axes: {tuple(mesh.axis_names)})")
        self.chain = chain
        self.mesh = mesh
        self.axis = axis
        for op in chain.ops:
            if (getattr(op, "shard_axis", None) == "window"
                    and hasattr(op, "set_window_sharding")):
                op.set_window_sharding(mesh, win_axis or axis)
        chain._steps = {}        # drop programs traced before shardings were set
        chain.states = [
            jax.device_put(st, _state_sharding(op, st, mesh, key_axis or axis,
                                               window_key_axis=key_axis))
            if st is not None else None
            for op, st in zip(chain.ops, chain.states)]

    def push(self, batch: Batch) -> Batch:
        return self.chain.push(shard_batch(batch, self.mesh, self.axis))

    def flush(self):
        return self.chain.flush()

    def result(self):
        return self.chain.result()
