"""Sharded execution: place a compiled chain's state and batches over a device mesh.

The reference's parallelism knobs (operator ``parallelism`` replicas, KF/WF emitters)
become sharding rules (SURVEY §2.6): the batch capacity axis shards over ``dp``; keyed
state tables ([K, ...]) shard their key axis; window engines shard their archive by
key. XLA/GSPMD inserts the collectives (the scatter/gather across shards that the
reference performs with ``ff_send_out_to`` queue hops) over ICI.

Usage::

    mesh = make_mesh(8)
    sharded = ShardedChain(chain, mesh)     # re-places state, shards pushes
    out = sharded.push(batch)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch
from ..runtime.pipeline import CompiledChain


def _state_sharding(op, state, mesh: Mesh, axis: str,
                    window_key_axis: Optional[str] = None):
    """Shard rule for one operator's state pytree, dispatched on the op's declared
    ``shard_axis``:

    - ``"key"`` (Key_Farm/Key_FFAT): leaves whose leading dim is the op's key-table
      size shard their key axis (KF_Emitter whole-key routing as a placement rule);
      everything else replicates.
    - ``"window"`` (Win_Farm): the fired-window [W] axis partitions *inside* the
      program via the ``with_sharding_constraint`` set by
      :meth:`Win_Seq.set_window_sharding`. The archive rings REPLICATE by default
      (every chip sees every tuple — the WF_Emitter multicast,
      ``wf/wf_nodes.hpp:182-204``, as a sharding rule); with an explicit
      ``window_key_axis`` (2-D key x win layouts) a KEYED farm's [K, ...] archive
      shards its key axis instead — the reference distributes a keyed Win_Farm's
      tuples by ``hash(key) % pardegree`` before the window round-robin
      (``wf/wf_nodes.hpp:157-204``), so at large K full replication wastes HBM.
    """
    shard_axis = getattr(op, "shard_axis", "key")
    num_keys = getattr(op, "num_keys", None)
    if shard_axis == "window":
        key_ax = window_key_axis

        def place_win(leaf):
            if (key_ax is not None and num_keys is not None and num_keys > 1
                    and getattr(leaf, "ndim", 0) >= 1
                    and leaf.shape[0] == num_keys
                    and num_keys % mesh.shape[key_ax] == 0):
                return NamedSharding(mesh, P(key_ax))
            return NamedSharding(mesh, P())
        return jax.tree.map(place_win, state)

    def place(leaf):
        if (shard_axis == "key" and num_keys is not None
                and getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] == num_keys
                and num_keys % mesh.shape.get(axis, mesh.devices.size) == 0):
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())
    return jax.tree.map(place, state)


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_batch(batch: Batch, mesh: Mesh, axis: str = "dp") -> Batch:
    s = batch_sharding(mesh, axis)
    return jax.tree.map(lambda a: jax.device_put(a, s), batch)


class ShardedChain:
    """Wraps a :class:`CompiledChain`, placing its states on the mesh so every
    ``push``/``flush`` runs as one GSPMD-partitioned program.

    On a 1-D mesh, ``axis`` carries both the batch capacity axis and the state
    shard axis. On a 2-D mesh (``make_mesh_2d``), pass ``key_axis`` (and/or
    ``win_axis``) to place key tables / fired-window rows on a different mesh
    axis than the batch: batch over ``dp`` (operator replication), key state
    over ``key`` (KF whole-key routing), window rows over ``win`` (WF window
    ownership) — the dp x ep / dp x sp layouts of the scaling playbook.

    A KEYED window farm on a ``key x win`` mesh gets BOTH: its [K, ...] archive
    shards over ``key_axis`` (explicit key_axis only — on a 1-D mesh the
    archive stays replicated, the WF-multicast rule) while its fired-window [W]
    rows shard over ``win_axis``."""

    def __init__(self, chain: CompiledChain, mesh: Mesh, axis: str = "dp",
                 win_axis: Optional[str] = None, key_axis: Optional[str] = None):
        # validate axis names up front: a typo would otherwise surface as a bare
        # KeyError from inside jax.tree.map during device_put
        for name, val in (("axis", axis), ("win_axis", win_axis),
                          ("key_axis", key_axis)):
            if val is not None and val not in mesh.axis_names:
                raise ValueError(
                    f"ShardedChain: {name}={val!r} is not an axis of the mesh "
                    f"(axes: {tuple(mesh.axis_names)})")
        self.chain = chain
        self.mesh = mesh
        self.axis = axis
        for op in chain.ops:
            if (getattr(op, "shard_axis", None) == "window"
                    and hasattr(op, "set_window_sharding")):
                op.set_window_sharding(mesh, win_axis or axis)
        chain._steps = {}        # drop programs traced before shardings were set
        chain.states = [
            jax.device_put(st, _state_sharding(op, st, mesh, key_axis or axis,
                                               window_key_axis=key_axis))
            if st is not None else None
            for op, st in zip(chain.ops, chain.states)]

    def push(self, batch: Batch) -> Batch:
        return self.chain.push(shard_batch(batch, self.mesh, self.axis))

    def flush(self):
        return self.chain.flush()

    def result(self):
        return self.chain.result()
