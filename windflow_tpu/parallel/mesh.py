"""Device-mesh helpers — the scale-out substrate.

The reference scales inside one shared-memory process with FastFlow threads; the
TPU-native generalization is a ``jax.sharding.Mesh`` over chips with named axes and
XLA-inserted collectives over ICI (SURVEY §2.6, §5). Axis vocabulary:

- ``"dp"``   — data parallelism: the micro-batch capacity axis (operator replication,
  reference ``parallelism`` of every operator).
- ``"key"``  — key partitioning: the [K] state-table axis (KF_Emitter whole-key
  routing, ``wf/kf_nodes.hpp:74-90``).
- ``"win"``  — window parallelism: the [W] fired-window axis (WF_Emitter round-robin
  window ownership, ``wf/wf_nodes.hpp:182-204``).
- ``"part"`` — intra-window partitioning (Win_MapReduce MAP stage,
  ``wf/wm_nodes.hpp:45-181``) — combines over ICI with psum.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp",
              devices: Sequence = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` (default: all)."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_mesh_2d(shape, axes=("dp", "key"), devices: Sequence = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    n = shape[0] * shape[1]
    return Mesh(np.array(devs[:n]).reshape(shape), tuple(axes))


def leading_axis_sharding(mesh: Mesh, axis: str) -> NamedSharding:
    """Shard the leading array axis over mesh axis ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
