"""Emitters — batch-level routing between pipeline segments (reference L2).

The reference's emitters scatter *tuples* to replica queues; here they scatter whole
micro-batches (or partition one batch into per-destination sub-batches) between
compiled segments — used by the threaded host runtime and multi-program topologies.
All partitioning math runs on device (jitted), host code only moves batch handles.

- :class:`Standard_Emitter` — FORWARD / KEYBY (``wf/standard_emitter.hpp:42-132``):
  KEYBY partitions a batch by ``hash(key) % n_dest`` into n_dest sub-batches via the
  sort-based compaction the reference's own scattering study favors
  (``wf/standard_nodes_gpu.hpp:52-238``, ``results_scattering.org``).
- :class:`Broadcast_Emitter` — copy-to-all (``wf/broadcast_emitter.hpp:42-110``); no
  refcounted wrapper needed: JAX arrays are immutable and shared.
- :class:`Splitting_Emitter` — user split function routes tuples to branches
  (``wf/splitting_emitter.hpp:41-152``); masks, optionally multicast.
- :class:`Tree_Emitter` — two-level composition: root emitter then per-destination
  child emitters (``wf/tree_emitter.hpp:42-229``).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t
from ..batch import Batch, tuple_refs
from ..ops.compaction import partition_by_destination


class Basic_Emitter:
    """Pluggable routing node (``wf/basic_emitter.hpp:40-57``): maps one input batch
    to a list of (destination, batch) pairs."""

    def __init__(self, n_dest: int):
        self.n_dest = int(n_dest)

    def getNDestinations(self) -> int:
        return self.n_dest

    def clone(self) -> "Basic_Emitter":
        import copy
        return copy.copy(self)

    def route(self, batch: Batch) -> List[Batch]:
        raise NotImplementedError


class Standard_Emitter(Basic_Emitter):
    def __init__(self, n_dest: int, mode: routing_modes_t = routing_modes_t.FORWARD,
                 routing_func: Callable = None, capacity_per_dest: int = None,
                 partition: str = "sort"):
        super().__init__(n_dest)
        self.mode = mode
        self.routing_func = routing_func or (lambda h, n: h % n)
        self.capacity_per_dest = capacity_per_dest
        # "sort" (stable argsort grouping) or "onehot" (sort-free cumsum ranks) —
        # the two formulations of the reference's scattering study
        # (src/GPU_Tests/scattering); bench.py A/Bs them per fan-out
        if partition not in ("sort", "onehot"):
            raise ValueError(f"Standard_Emitter: partition must be 'sort' or "
                             f"'onehot', got {partition!r}")
        self.partition = partition
        self._rr = 0
        self._jit_part = jax.jit(self._partition, static_argnums=(1,))

    def _partition(self, batch: Batch, cap: int):
        from ..ops.compaction import partition_by_destination_onehot
        part = (partition_by_destination_onehot if self.partition == "onehot"
                else partition_by_destination)
        dest = self.routing_func(batch.key, self.n_dest).astype(jnp.int32)
        idx, ov = part(dest, batch.valid, self.n_dest, cap)
        return [batch.select(idx[d], ov[d]) for d in range(self.n_dest)]

    def route(self, batch: Batch) -> List[Optional[Batch]]:
        if self.mode == routing_modes_t.KEYBY:
            cap = self.capacity_per_dest or batch.capacity
            return self._jit_part(batch, cap)
        # FORWARD: round-robin whole batches (reference sends tuples round-robin;
        # batch granularity keeps device work contiguous)
        out = [None] * self.n_dest
        out[self._rr % self.n_dest] = batch
        self._rr += 1
        return out


class Broadcast_Emitter(Basic_Emitter):
    def route(self, batch: Batch) -> List[Batch]:
        return [batch] * self.n_dest


class Splitting_Emitter(Basic_Emitter):
    def __init__(self, split_fn: Callable, n_dest: int):
        super().__init__(n_dest)
        self.split_fn = split_fn
        self._jit_sel = jax.jit(self._select)

    def _select(self, batch: Batch):
        sel = jax.vmap(self.split_fn)(tuple_refs(batch))
        outs = []
        for i in range(self.n_dest):
            if getattr(sel, "ndim", 1) == 2:
                keep = sel[:, i].astype(jnp.bool_)
            else:
                keep = jnp.asarray(sel, jnp.int32) == i
            outs.append(batch.mask(keep))
        return outs

    def route(self, batch: Batch) -> List[Batch]:
        return self._jit_sel(batch)


class Tree_Emitter(Basic_Emitter):
    """Root emitter fans to child emitters; destination j of child i is global
    destination ``sum(n_dest of children < i) + j`` (``wf/tree_emitter.hpp``)."""

    def __init__(self, root: Basic_Emitter, children: Sequence[Basic_Emitter]):
        if root.getNDestinations() != len(children):
            raise ValueError("root destinations must equal number of children")
        super().__init__(sum(c.getNDestinations() for c in children))
        self.root = root
        self.children = [c.clone() for c in children]

    def route(self, batch: Batch) -> List[Optional[Batch]]:
        out: List[Optional[Batch]] = []
        for child, b in zip(self.children, self.root.route(batch)):
            if b is None:
                out.extend([None] * child.getNDestinations())
            else:
                out.extend(child.route(b))
        return out
