"""Emitters — batch-level routing between pipeline segments (reference L2).

The reference's emitters scatter *tuples* to replica queues; here they scatter whole
micro-batches (or partition one batch into per-destination sub-batches) between
compiled segments — used by the threaded host runtime and multi-program topologies.
All partitioning math runs on device (jitted), host code only moves batch handles.

- :class:`Standard_Emitter` — FORWARD / KEYBY (``wf/standard_emitter.hpp:42-132``):
  KEYBY partitions a batch by ``hash(key) % n_dest`` into n_dest sub-batches via the
  sort-based compaction the reference's own scattering study favors
  (``wf/standard_nodes_gpu.hpp:52-238``, ``results_scattering.org``).
- :class:`Broadcast_Emitter` — copy-to-all (``wf/broadcast_emitter.hpp:42-110``); no
  refcounted wrapper needed: JAX arrays are immutable and shared.
- :class:`Splitting_Emitter` — user split function routes tuples to branches
  (``wf/splitting_emitter.hpp:41-152``); masks, optionally multicast.
- :class:`Tree_Emitter` — two-level composition: root emitter then per-destination
  child emitters (``wf/tree_emitter.hpp:42-229``).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t
from ..batch import Batch, concat_batches, tuple_refs
from ..ops.compaction import partition_by_destination


def _pad_batch_pow2(b: Batch) -> Batch:
    """Pad a batch's capacity up to the next power of two with invalid lanes."""
    C = b.capacity
    P = 1
    while P < C:
        P *= 2
    if P == C:
        return b
    pad = P - C
    pz = lambda a: jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
    return Batch(key=pz(b.key), id=pz(b.id), ts=pz(b.ts),
                 payload=jax.tree.map(pz, b.payload), valid=pz(b.valid))


class Basic_Emitter:
    """Pluggable routing node (``wf/basic_emitter.hpp:40-57``): maps one input batch
    to a list of (destination, batch) pairs."""

    def __init__(self, n_dest: int):
        self.n_dest = int(n_dest)

    def getNDestinations(self) -> int:
        return self.n_dest

    def clone(self) -> "Basic_Emitter":
        import copy
        return copy.copy(self)

    def route(self, batch: Batch) -> List[Batch]:
        raise NotImplementedError


class Standard_Emitter(Basic_Emitter):
    """FORWARD / KEYBY routing. KEYBY is LOSSLESS even when ``capacity_per_dest``
    is smaller than a destination's share of one batch: overflowing lanes are
    re-partitioned in further passes and each destination receives the rounds
    concatenated into one sub-batch — the host loop is the blocking bounded-queue
    backpressure of the reference (``FF_BOUNDED_BUFFER``, ``wf/standard_emitter.
    hpp:42-132``: the reference blocks, it never drops). ``overflow_rounds``
    counts the extra passes (0 on the fast path, which also does no host sync)."""

    def __init__(self, n_dest: int, mode: routing_modes_t = routing_modes_t.FORWARD,
                 routing_func: Callable = None, capacity_per_dest: int = None,
                 partition: str = "sort"):
        super().__init__(n_dest)
        self.mode = mode
        self.routing_func = routing_func or (lambda h, n: h % n)
        self.capacity_per_dest = capacity_per_dest
        # "sort" (stable argsort grouping) or "onehot" (sort-free cumsum ranks) —
        # the two formulations of the reference's scattering study
        # (src/GPU_Tests/scattering); bench.py A/Bs them per fan-out
        if partition not in ("sort", "onehot"):
            raise ValueError(f"Standard_Emitter: partition must be 'sort' or "
                             f"'onehot', got {partition!r}")
        self.partition = partition
        self._rr = 0
        self.overflow_rounds = 0
        self._jit_part = jax.jit(self._partition, static_argnums=(1,))
        self._jit_part_resid = jax.jit(self._partition_resid, static_argnums=(1,))

    def _dest(self, batch: Batch) -> jax.Array:
        return self.routing_func(batch.key, self.n_dest).astype(jnp.int32)

    def _partition(self, batch: Batch, cap: int):
        from ..ops.compaction import partition_by_destination_onehot
        part = (partition_by_destination_onehot if self.partition == "onehot"
                else partition_by_destination)
        idx, ov = part(self._dest(batch), batch.valid, self.n_dest, cap)
        return [batch.select(idx[d], ov[d]) for d in range(self.n_dest)]

    def _partition_resid(self, batch: Batch, cap: int):
        """Partition + residue: lanes whose within-destination rank exceeds the
        lane budget stay valid in the returned residue mask for the next pass."""
        from ..ops.segment import segment_rank
        subs = self._partition(batch, cap)
        dest = self._dest(batch)
        in_range = (dest >= 0) & (dest < self.n_dest)
        rank = segment_rank(jnp.where(batch.valid & in_range, dest, self.n_dest),
                            batch.valid)
        resid = batch.valid & in_range & (rank >= cap)
        return subs, resid, jnp.sum(resid.astype(jnp.int32))

    def route(self, batch: Batch) -> List[Optional[Batch]]:
        if self.mode == routing_modes_t.KEYBY:
            cap = self.capacity_per_dest or batch.capacity
            if cap >= batch.capacity:      # overflow impossible: no sync, one pass
                return self._jit_part(batch, cap)
            outs, cur = None, batch
            while True:
                subs, resid, n_resid = self._jit_part_resid(cur, cap)
                outs = (subs if outs is None else
                        [concat_batches(a, b) for a, b in zip(outs, subs)])
                if int(n_resid) == 0:
                    if outs and outs[0].capacity > cap:   # multi-round concat
                        # pad multi-round outputs to a pow2 capacity so a
                        # downstream compiled consumer sees O(log rounds)
                        # distinct shapes, not one per round count (the same
                        # discipline as Ordering_Node._pad_pow2)
                        outs = [_pad_batch_pow2(b) for b in outs]
                    return outs
                self.overflow_rounds += 1
                cur = cur.replace(valid=resid)
        # FORWARD: round-robin whole batches (reference sends tuples round-robin;
        # batch granularity keeps device work contiguous)
        out = [None] * self.n_dest
        out[self._rr % self.n_dest] = batch
        self._rr += 1
        return out


class Broadcast_Emitter(Basic_Emitter):
    def route(self, batch: Batch) -> List[Batch]:
        return [batch] * self.n_dest


class Splitting_Emitter(Basic_Emitter):
    def __init__(self, split_fn: Callable, n_dest: int):
        super().__init__(n_dest)
        self.split_fn = split_fn
        self._jit_sel = jax.jit(self._select)

    def _select(self, batch: Batch):
        sel = jax.vmap(self.split_fn)(tuple_refs(batch))
        outs = []
        for i in range(self.n_dest):
            if getattr(sel, "ndim", 1) == 2:
                keep = sel[:, i].astype(jnp.bool_)
            else:
                keep = jnp.asarray(sel, jnp.int32) == i
            outs.append(batch.mask(keep))
        return outs

    def route(self, batch: Batch) -> List[Batch]:
        return self._jit_sel(batch)


class Tree_Emitter(Basic_Emitter):
    """Root emitter fans to child emitters; destination j of child i is global
    destination ``sum(n_dest of children < i) + j`` (``wf/tree_emitter.hpp``)."""

    def __init__(self, root: Basic_Emitter, children: Sequence[Basic_Emitter]):
        if root.getNDestinations() != len(children):
            raise ValueError("root destinations must equal number of children")
        super().__init__(sum(c.getNDestinations() for c in children))
        self.root = root
        self.children = [c.clone() for c in children]

    def route(self, batch: Batch) -> List[Optional[Batch]]:
        out: List[Optional[Batch]] = []
        for child, b in zip(self.children, self.root.route(batch)):
            if b is None:
                out.extend([None] * child.getNDestinations())
            else:
                out.extend(child.route(b))
        return out
