from .mesh import make_mesh, make_mesh_2d, leading_axis_sharding, replicated
from .sharding import (ShardedChain, shard_batch, batch_sharding,
                       ShardAssignment, ReshardPlan, make_splitter,
                       affected_shards, resolve_shards)
from .emitters import (Basic_Emitter, Standard_Emitter, Broadcast_Emitter,
                       Splitting_Emitter, Tree_Emitter)
from .ordering import Ordering_Node
from .collective import (wmr_map_reduce, ring_pane_windows, keyed_all_to_all,
                         keyed_all_to_all_lossless)
from . import multihost

__all__ = [
    "make_mesh", "make_mesh_2d", "leading_axis_sharding", "replicated",
    "ShardedChain", "shard_batch", "batch_sharding",
    "ShardAssignment", "ReshardPlan", "make_splitter", "affected_shards",
    "resolve_shards",
    "Basic_Emitter", "Standard_Emitter", "Broadcast_Emitter",
    "Splitting_Emitter", "Tree_Emitter", "Ordering_Node",
    "wmr_map_reduce", "ring_pane_windows", "keyed_all_to_all",
    "keyed_all_to_all_lossless", "multihost",
]
