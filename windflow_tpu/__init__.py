"""windflow_tpu — a TPU-native data-stream-processing framework.

Same capability surface as WindFlow (reference: cosimoagati/WindFlow, C++17 header-only
stream processing on multicores + CUDA GPUs), re-architected for TPU: streams are
sequences of fixed-capacity SoA micro-batches; operator chains compile to single XLA
programs; keyed state lives in HBM tables; windows are batched rows fed to vmapped /
Pallas kernels; parallelism is expressed with ``jax.sharding`` over device meshes.
See SURVEY.md for the blueprint.
"""

from .basic import (Mode, win_type_t, opt_level_t, routing_modes_t, pattern_t,
                    win_event_t, ordering_mode_t, role_t,
                    current_time_usecs, current_time_nsecs, WinOperatorConfig)
from .batch import Batch, TupleRef, tuple_refs, concat_batches, split_batch
from .context import RuntimeContext, LocalStorage
from .shipper import Shipper
from .operators import (Basic_Operator, Source, DeviceSource, GeneratorSource,
                        RecordSource,
                        Map, KeyedMap, KeyBy, Filter, FilterMap, Compact, FlatMap,
                        Accumulator, StreamTableJoin, IntervalJoin,
                        SessionWindow, TopN, Distinct, Sink, ReduceSink)
from .operators.map import BatchMap
from .operators.window import WindowSpec, Iterable
from .operators.win_seq import Win_Seq
from .operators.win_seqffat import Win_SeqFFAT
from .operators.win_patterns import (Win_Farm, Key_Farm, Key_FFAT, Pane_Farm,
                                     Win_MapReduce, Nested_Farm)
from .runtime import CompiledChain, Pipeline, Stats_Record
from .stats import xprof_trace
from .observability import (MetricsRegistry, MonitoringConfig, Reporter,
                            EventJournal, LogHistogram, read_journal,
                            topology_dot, topology_json)
from .runtime.async_sink import AsyncResultShipper, ShippedResult
from .runtime.checkpoint import save_chain, load_chain, CheckpointCorrupt
from .runtime.faults import (FaultPlan, FaultSpec, FaultInjector,
                             InjectedFault, WatchdogTimeout, DeadLetterQueue)
from .control import (ControlConfig, AdmissionController, TokenBucket,
                      PositionBucket, BackpressureGovernor, CapacityAutotuner,
                      Rebatcher, TuningCache)
from .operators.source import prefetch_to_device
from .parallel import make_mesh, make_mesh_2d
from .parallel.sharding import ShardedChain, shard_batch
from .runtime.pipegraph import PipeGraph, MultiPipe
from .runtime.threaded import ThreadedPipeline
from .runtime.supervisor import SupervisedPipeline, RestartExhausted
from .runtime import builders
from .runtime.builders import (Source_Builder, Filter_Builder, Map_Builder,
                               FlatMap_Builder, Accumulator_Builder,
                               WinSeq_Builder, WinSeqFFAT_Builder,
                               WinFarm_Builder, KeyFarm_Builder, KeyFFAT_Builder,
                               PaneFarm_Builder, WinMapReduce_Builder,
                               Sink_Builder, ReduceSink_Builder)
from . import analysis
from .analysis import validate as validate_graph

__version__ = "0.1.0"
