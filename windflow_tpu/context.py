"""RuntimeContext and LocalStorage — per-replica info for "rich" user functions.

Counterparts of ``wf/context.hpp:49-102`` and ``wf/local_storage.hpp:49-139``. In the
reference a rich function receives the replica's parallelism, its index and a typed
per-replica key-value store. Here a "replica" is a shard of the compiled program;
``RuntimeContext`` carries the same identity ([replica_index, parallelism]) plus the
device-side state slot the rich function may read/update (a pytree threaded through the
compiled step, since XLA programs are pure).
"""

from __future__ import annotations

from typing import Any, Dict


class LocalStorage:
    """Per-replica untyped key-value store (``wf/local_storage.hpp:49-139``).

    Host-side only (user closing/init functions run on host). ``get(name, default)``
    inserts the default on miss like the reference's default-construct-on-miss
    (``wf/local_storage.hpp:74-90``)."""

    def __init__(self):
        self._store: Dict[str, Any] = {}

    def get(self, name: str, default: Any = None) -> Any:
        if name not in self._store:
            self._store[name] = default
        return self._store[name]

    def put(self, name: str, value: Any) -> None:     # wf/local_storage.hpp:93
        self._store[name] = value

    def remove(self, name: str) -> None:              # wf/local_storage.hpp:117
        self._store.pop(name, None)

    def is_contained(self, name: str) -> bool:
        return name in self._store

    def get_size(self) -> int:
        return len(self._store)


class RuntimeContext:
    """Identity of the executing replica handed to rich user functions
    (``wf/context.hpp:49-102``).

    ``state`` is the optional per-replica *device* state pytree for rich map/filter
    functions (the functional replacement for mutating members of a C++ functor): a
    rich function has signature ``f(tuple, ctx)`` and may return
    ``(result, new_state)`` with ``ctx.state`` as input state.
    """

    def __init__(self, parallelism: int = 1, index: int = 0, state: Any = None):
        self._parallelism = parallelism
        self._index = index
        self.state = state
        self._storage = LocalStorage()

    def getParallelism(self) -> int:
        return self._parallelism

    def getReplicaIndex(self) -> int:
        return self._index

    def getLocalStorage(self) -> LocalStorage:
        return self._storage

    # pythonic aliases
    parallelism = property(getParallelism)
    replica_index = property(getReplicaIndex)
    storage = property(getLocalStorage)
