#!/usr/bin/env python3
"""wf_state — stateful-operator / event-time inspection CLI.

Reads a monitoring run's artifacts (``snapshots.jsonl`` time series +
``snapshot.json`` + ``events.jsonl``) and renders:

- the **watermark propagation map**: per-operator event-time frontiers, the
  graph-level min-watermark frontier (who is holding event time back), and
  per-edge watermark skew;
- **state-pressure trends**: table occupancy / pending-ring depth / archive
  fill / open sessions over the run, with overflow-risk flags;
- the **lateness report**: per-(operator, stream) observed-lateness
  histograms with quantiles and ``recommend_delay(q)`` — the smallest
  ``delay=`` covering quantile ``q`` of the observed lateness — joined with
  the operator's drop counters and any ``lateness_drop`` journal events.

Produce the inputs with event-time monitoring on::

    WF_MONITORING=1 WF_MONITORING_EVENT_TIME=1 python my_run.py
    python scripts/wf_state.py --monitoring-dir wf_monitoring

Stdlib only (``observability/event_time.py`` is loaded by file path — the
``wf_trace.py`` convention), so this works on any box the artifacts were
copied to, without JAX installed.

Exit codes: 0 = report rendered, 2 = missing/unreadable inputs or usage
error (``tests/test_event_time.py`` pins the contract).
"""

import argparse
import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_event_time():
    """Load observability/event_time.py by file path — no package import,
    no JAX (the module keeps its jax imports inside the device helpers)."""
    path = os.path.join(REPO, "windflow_tpu", "observability",
                        "event_time.py")
    spec = importlib.util.spec_from_file_location("wf_event_time", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["wf_event_time"] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_device_health():
    """Load observability/device_health.py by file path — THE shared
    snapshot/journal loader (+ fleet merge) of wf_state/wf_trace/wf_health,
    so the three CLIs can never drift on torn-line handling."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in ("journal", "device_health", "slo"):
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return sys.modules["wf_obs.device_health"], sys.modules["wf_obs.slo"]


# ------------------------------------------------------------ report pieces

#: occupancy-style percentages above this flag OVERFLOW-RISK in the
#: pressure report (state tables drop, not grow, when full); the default
#: for --risk-threshold
RISK_PCT = 80.0


def _et_rows(snap):
    """(name, event_time section) for every operator carrying one."""
    return [(r["name"], r["event_time"]) for r in snap.get("operators", [])
            if r.get("event_time")]


def watermark_map(snap):
    lines = ["== watermark propagation map =="]
    rows = _et_rows(snap)
    if not rows:
        lines.append("  (no event_time sections — run with "
                     "WF_MONITORING_EVENT_TIME=1 / "
                     "MonitoringConfig(event_time=True))")
        return lines
    for name, sec in rows:
        bits = []
        if "watermark_ts" in sec:
            bits.append(f"wm={sec['watermark_ts']}")
        if "fire_frontier_ts" in sec:
            bits.append(f"frontier={sec['fire_frontier_ts']}")
        if "lag" in sec:
            bits.append(f"lag={sec['lag']}")
        if "applied_version" in sec:
            bits.append(f"version={sec['applied_version']}")
        if "delay" in sec:
            bits.append(f"delay={sec['delay']}")
        detail = "  ".join(bits) if bits else "(no event-time frontier)"
        lines.append(f"  {name:<28} {detail}")
    et = snap.get("event_time") or {}
    if "min_watermark_ts" in et:
        who = et.get("frontier_operator")
        lines.append(f"  graph min-watermark frontier: "
                     f"{et['min_watermark_ts']}"
                     + (f" (held by {who})" if who else ""))
    for edge, skew in sorted((et.get("edge_skew_ts") or {}).items()):
        lines.append(f"  edge {edge:<24} watermark skew {skew:+d}")
    return lines


#: (section key, display label) pairs of the pressure gauges we trend
_PRESSURE_KEYS = (
    ("occupancy_pct", "occupancy%"),
    ("pending_depth", "pending"),
    ("l_fill_pct", "l-archive%"),
    ("r_fill_pct", "r-archive%"),
    ("open_sessions", "open-sessions"),
)


#: tier-section keys trended by the tier report: device-side occupancy +
#: cold size (gauges) and the movement counters
_TIER_GAUGES = (("hot_pct", "hot%"), ("hot_used", "hot-used"),
                ("outbox_depth", "outbox"), ("cold_keys", "cold-keys"),
                ("cold_rows", "cold-rows"),
                ("l_cold_rows", "l-cold"), ("r_cold_rows", "r-cold"))
_TIER_COUNTERS = (("state_spills", "spills"),
                  ("state_readmits", "readmits"),
                  ("state_compactions", "compactions"))


def pressure_trends(snap, series, risk_pct=RISK_PCT):
    lines = ["== state-pressure trends =="]
    hist = {}                       # (op, key) -> [values over time]
    for s in series or [snap]:
        for name, sec in _et_rows(s):
            for key, _label in _PRESSURE_KEYS:
                if key in sec:
                    hist.setdefault((name, key), []).append(sec[key])
    if not hist:
        lines.append("  (no pressure gauges in the snapshots)")
        return lines
    for name, sec in _et_rows(snap):
        for key, label in _PRESSURE_KEYS:
            if key not in sec:
                continue
            vals = hist.get((name, key), [sec[key]])
            flag = ""
            if key.endswith("pct") and max(vals) >= risk_pct:
                flag = "  [OVERFLOW-RISK]"
            if (key == "pending_depth" and sec.get("pending_capacity")
                    and max(vals) >= risk_pct / 100.0
                    * sec["pending_capacity"]):
                flag = "  [OVERFLOW-RISK]"
            lines.append(f"  {name:<28} {label:<14} "
                         f"first={vals[0]} last={vals[-1]} "
                         f"max={max(vals)}{flag}")
        drops = {k: v for k, v in sec.items()
                 if k.endswith("_drops") and v}
        if drops:
            lines.append(f"  {name:<28} drops          "
                         + "  ".join(f"{k}={v}" for k, v in
                                     sorted(drops.items())))
    return lines


def tier_report(snap, series, risk_pct=RISK_PCT):
    """Tiered-state sections: per-operator hot/cold occupancy and the
    spill/readmit/compaction movement over the run (the ``tier`` sub-dict
    the tiered operators put in their event_time snapshot rows)."""
    lines = ["== tiered state (hot/cold) =="]
    rows = [(name, sec["tier"]) for name, sec in _et_rows(snap)
            if isinstance(sec.get("tier"), dict)]
    if not rows:
        lines.append("  (no tiered operators — enable with tiered= / "
                     "WF_STATE_TIERED=1)")
        return lines
    hist = {}
    for s in series or [snap]:
        for name, sec in _et_rows(s):
            t = sec.get("tier")
            if not isinstance(t, dict):
                continue
            for key, _label in _TIER_GAUGES + _TIER_COUNTERS:
                if key in t:
                    hist.setdefault((name, key), []).append(t[key])
    for name, t in rows:
        for key, label in _TIER_GAUGES:
            if key not in t:
                continue
            vals = hist.get((name, key), [t[key]])
            flag = ("  [OVERFLOW-RISK]"
                    if key == "hot_pct" and max(vals) >= risk_pct else "")
            lines.append(f"  {name:<28} {label:<14} "
                         f"first={vals[0]} last={vals[-1]} "
                         f"max={max(vals)}{flag}")
        moves = []
        for key, label in _TIER_COUNTERS:
            if key in t:
                vals = hist.get((name, key), [t[key]])
                moves.append(f"{label}={vals[-1]} (+{vals[-1] - vals[0]} "
                             f"over run)")
        if moves:
            lines.append(f"  {name:<28} movement       " + "  ".join(moves))
    return lines


def lateness_report(snap, journal, et, q):
    lines = [f"== lateness report (recommend_delay at q={q}) =="]
    data = {}
    any_hist = False
    for name, sec in _et_rows(snap):
        for stream, summ in (sec.get("lateness") or {}).items():
            any_hist = True
            counts = summ.get("counts") or []
            rec = et.recommend_delay(counts, q)
            cur = sec.get("delay")
            verdict = ""
            if cur is not None:
                verdict = (" — current delay covers it" if cur >= rec
                           else f" — RAISE delay from {cur}")
            lines.append(
                f"  {name:<28} stream={stream:<6} samples={summ.get('total')}"
                f" p50={summ.get('p50')} p95={summ.get('p95')}"
                f" p99={summ.get('p99')} max={summ.get('max')}"
                f"  recommend_delay={rec}{verdict}")
            data[f"{name}/{stream}"] = {
                "recommend_delay": rec, "current_delay": cur,
                "total": summ.get("total"), "p50": summ.get("p50"),
                "p95": summ.get("p95"), "p99": summ.get("p99"),
                "max": summ.get("max")}
    if not any_hist:
        lines.append("  (no lateness histograms recorded)")
    drops = [e for e in journal if e.get("event") == "lateness_drop"]
    if drops:
        lines.append("  drop journal:")
        for e in drops:
            coord = (f" at/before pos={e['pos']}"
                     if e.get("pos") is not None else "")
            lines.append(f"    {e.get('op', '?'):<26} {e.get('kind', '?'):<16}"
                         f" +{e.get('n', 0)} (total {e.get('total', '?')})"
                         f"{coord}")
    return lines, data


def shard_section(snap, journal):
    """Per-shard supervision rows (the ``shards`` snapshot section written
    by the sharded supervisors; host-tagged keys in a fleet merge so the
    view names WHICH shard is hot) + the shard_restore/reshard timeline."""
    lines = ["== shard supervision =="]
    shards = snap.get("shards") or {}
    if not shards:
        lines.append("  (no shards section — run the supervised driver "
                     "with shards=N / WF_SHARDS=N and monitoring on)")
        return lines
    hot = max(shards, key=lambda k: shards[k].get("occupancy_tuples", 0))
    for k in sorted(shards, key=lambda x: (len(x), x)):
        r = shards[k]
        flag = "  [HOT]" if k == hot and len(shards) > 1 else ""
        lines.append(
            f"  shard {k:<12} tuples={r.get('occupancy_tuples', 0):<8} "
            f"restarts={r.get('restarts', 0)} "
            f"last_recovery={r.get('last_recovery_s', 0.0) * 1e3:.2f}ms "
            f"dead_letters={r.get('dead_letters', 0)} "
            f"reshard_moves={r.get('reshard_moves', 0)} "
            f"committed_pos={r.get('committed_pos', 0)}{flag}")
    n_rest = sum(1 for e in journal if e.get("event") == "shard_restore")
    n_rs = sum(1 for e in journal if e.get("event") == "reshard"
               and e.get("phase") != "end")
    if n_rest or n_rs:
        lines.append(f"  journal: {n_rest} shard_restore event(s), "
                     f"{n_rs} reshard event(s)")
    return lines


def incidents_section(slo_mod, mon_dir):
    """Cross-reference to the SLO engine's forensic bundles (count, last
    incident path + triggering SLO, torn captures) read from the bundle
    manifests under ``<mon_dir>/incidents`` — the wf_health.py section,
    mirrored here so the state inspector names the forensics too."""
    lines = ["== incidents (SLO forensic bundles) =="]
    summ = slo_mod.incidents_summary(mon_dir)
    if not summ["count"] and not summ["torn"]:
        lines.append("  (none captured — enable with WF_SLO=1 / "
                     "MonitoringConfig(slo=...); analyze with "
                     "scripts/wf_slo.py)")
        return lines
    lines.append(f"  {summ['count']} committed bundle(s)"
                 + (f", {summ['torn']} TORN (crash mid-capture)"
                    if summ["torn"] else ""))
    last = summ.get("last")
    if last:
        lines.append(f"  last: {last['path']}")
        lines.append(f"        triggered by SLO {last.get('slo')!r} "
                     f"(state {last.get('state')})")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_state",
        description="windflow_tpu state-inspector / event-time CLI")
    ap.add_argument("--monitoring-dir", default="wf_monitoring",
                    help="monitoring output directory (snapshots.jsonl + "
                         "snapshot.json + events.jsonl)")
    ap.add_argument("--merge", nargs="+", default=None, metavar="DIR",
                    help="merge N per-host monitoring directories (or "
                         "snapshots.jsonl paths) into one fleet view — "
                         "counters summed, watermark frontier min'd, "
                         "occupancy/pressure max'd (device_health."
                         "merge_snapshots) — instead of --monitoring-dir")
    ap.add_argument("--q", type=float, default=0.99,
                    help="lateness quantile recommend_delay must cover "
                         "(default 0.99; 1.0 = every recorded straggler)")
    ap.add_argument("--risk-threshold", type=float, default=RISK_PCT,
                    metavar="PCT",
                    help=f"occupancy percentage flagged [OVERFLOW-RISK] in "
                         f"the pressure/tier reports (default {RISK_PCT})")
    ap.add_argument("--report", choices=("all", "watermarks", "pressure",
                                         "tier", "lateness", "shards",
                                         "incidents"),
                    default="all",
                    help="which section(s) to render (default all)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: the latest snapshot's "
                         "event_time sections + per-stream delay "
                         "recommendations")
    args = ap.parse_args(argv)

    if not (0.0 < args.q <= 1.0):
        print(f"wf_state: --q must be in (0, 1], got {args.q}",
              file=sys.stderr)
        return 2
    if not (0.0 < args.risk_threshold <= 100.0):
        print(f"wf_state: --risk-threshold must be in (0, 100], got "
              f"{args.risk_threshold}", file=sys.stderr)
        return 2
    try:
        et = _load_event_time()
        dh, slo_mod = _load_device_health()
    except (OSError, ImportError, SyntaxError) as e:
        # the 0/2 contract covers the helper modules too: a box the
        # artifacts were copied to without the windflow_tpu tree beside
        # this script gets the guidance, not a traceback
        print(f"wf_state: cannot load observability helpers from "
              f"{REPO!r}: {type(e).__name__}: {e}\n"
              f"(keep scripts/wf_state.py next to its windflow_tpu tree — "
              f"it reuses the lateness bucket math and the snapshot loader "
              f"by file path)",
              file=sys.stderr)
        return 2
    try:
        if args.merge:
            snap, series, journal = dh.merge_monitoring_dirs(args.merge)
        else:
            snap, series = dh.load_snapshots(args.monitoring_dir)
            journal = dh.load_journal(args.monitoring_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        where = args.merge or args.monitoring_dir
        print(f"wf_state: cannot load snapshots from "
              f"{where!r}: {type(e).__name__}: {e}\n"
              f"(run with WF_MONITORING=1 WF_MONITORING_EVENT_TIME=1, or "
              f"monitoring=MonitoringConfig(event_time=True))",
              file=sys.stderr)
        return 2

    lat_lines, lat_data = lateness_report(snap, journal, et, args.q)
    if args.json:
        out = {"graph": snap.get("graph"),
               "event_time": snap.get("event_time") or {},
               "operators": {name: sec for name, sec in _et_rows(snap)},
               "recommendations": lat_data,
               "risk_threshold": args.risk_threshold,
               "tier": {name: sec["tier"] for name, sec in _et_rows(snap)
                        if isinstance(sec.get("tier"), dict)},
               "shards": snap.get("shards") or {},
               "snapshots": len(series)}
        if not args.merge:
            out["incidents"] = slo_mod.incidents_summary(args.monitoring_dir)
        if snap.get("hosts"):
            out["hosts"] = snap["hosts"]
            out["merged_from"] = snap.get("merged_from")
        if snap.get("schema_mismatch"):
            out["schema_mismatch"] = snap["schema_mismatch"]
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    blocks = []
    if args.report in ("all", "watermarks"):
        blocks.append(watermark_map(snap))
    if args.report in ("all", "pressure"):
        blocks.append(pressure_trends(snap, series, args.risk_threshold))
    if args.report in ("all", "tier"):
        blocks.append(tier_report(snap, series, args.risk_threshold))
    if args.report in ("all", "lateness"):
        blocks.append(lat_lines)
    if args.report == "shards" or (args.report == "all"
                                   and snap.get("shards")):
        blocks.append(shard_section(snap, journal))
    if args.report in ("all", "incidents"):
        if args.merge:
            # per-host forensics: a merged fleet view has no single
            # incidents/ directory — say so when incidents were asked for
            # explicitly instead of rendering nothing (indistinguishable
            # from "no incidents on the fleet")
            if args.report == "incidents":
                blocks.append(
                    ["== incidents (SLO forensic bundles) ==",
                     "  (not available in the --merge fleet view — "
                     "bundles live under each host's own "
                     "<monitoring_dir>/incidents/; run wf_state "
                     "against each host's dir)"])
        else:
            blocks.append(incidents_section(slo_mod, args.monitoring_dir))
    head = (f"wf_state: merged {snap.get('merged_from')} host(s): "
            + ", ".join(h.get("host", "?") for h in snap.get("hosts", []))
            if args.merge else f"wf_state: {args.monitoring_dir!r}")
    print(f"{head} — graph "
          f"{snap.get('graph', '?')!r}, {len(series)} snapshot(s), "
          f"{len(journal)} journal event(s)")
    if snap.get("schema_mismatch"):
        # merge_snapshots flags mixed snapshot generations, never folds
        # them silently — keep the flag visible at the top of the report
        print(f"wf_state: MIXED-SCHEMA fleet — per-host snapshot schema "
              f"versions differ: "
              f"{json.dumps(snap['schema_mismatch'], sort_keys=True)}")
    for b in blocks:
        print()
        print("\n".join(b))
    return 0


if __name__ == "__main__":
    sys.exit(main())
