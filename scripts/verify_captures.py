"""Verify driver: library end-to-end on CPU + bench capture-persistence paths.

Run as ``python scripts/verify_captures.py`` from the repo root (sys.path gets
the repo root injected below — PYTHONPATH must stay unset, it breaks the axon
TPU plugin init).
"""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update('jax_platforms', 'cpu')

import json
import subprocess
import tempfile

import numpy as np
import jax.numpy as jnp
import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec


def run(op, total=96, K=2, batch=32):
    src = wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)}, total=total, num_keys=K)
    out = []
    def cb(view):
        if view is None:
            return
        out.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))
    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=batch).run()
    return sorted(out)


# 1. end-to-end result invariance under batch size
mk = lambda: wf.Win_Seq(lambda wid, it: it.sum("v"),
                        WindowSpec(8, 4, win_type_t.TB), num_keys=2)
oracle = run(mk(), batch=32)
assert oracle, "oracle produced no windows"
for b in (16, 48, 96):
    got = run(mk(), batch=b)
    assert got == oracle, f"batch={b} diverged from oracle"
print(f"end-to-end OK: {len(oracle)} window results invariant under batch 16/32/48/96")

# 2. bench module: record -> load -> stale emission round trip in a subprocess,
#    with CAPTURE_PATH pointed at a temp store (the committed seed untouched)
with tempfile.TemporaryDirectory() as td:
    code = f"""
import bench, json, sys
bench.CAPTURE_PATH = {os.path.join(td, 'last_good.json')!r}
bench.record('ysb', {{'tps': 1.0e8, 'step_s': 0.01, 'batch': 1048576}})
bench.record_headline({{'metric': 'YSB tuples/sec/chip', 'value': 100000000,
                        'unit': 'tuples/s', 'vs_baseline': 6.024}},
                      methodology='verify-driver')
sys.exit(bench.emit_stale_headline('verify-simulated outage'))
"""
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr
    line = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")][-1]
    payload = json.loads(line)
    assert payload["stale"] is True and payload["value"] == 100000000
    assert payload["methodology"] == "verify-driver"
print("stale-emission path OK (subprocess, temp store)")

# 3. the committed seed store parses and the real healthcheck path degrades to
#    rc=0 with a stale line when the probe fails (10s timeout, dead tunnel)
proc = subprocess.run(
    [sys.executable, "-c",
     "import bench; bench._device_healthcheck(timeout_s=10); print('DEVICE-UP')"],
    capture_output=True, text=True, cwd="/root/repo")
if "DEVICE-UP" in proc.stdout:
    print("device reachable — healthcheck passed (stale path not needed)")
else:
    assert proc.returncode == 0, f"rc={proc.returncode}: {proc.stderr[-500:]}"
    line = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")][-1]
    payload = json.loads(line)
    import bench
    stored = bench._load_store()["headline"]
    assert payload["stale"] is True
    assert payload["metric"] == "YSB tuples/sec/chip"
    assert payload["value"] == stored["value"], (payload, stored)
    print(f"real healthcheck degraded to stale stored capture OK "
          f"(value={payload['value']}, captured_at={payload['captured_at']})")

print("VERIFY PASS")
