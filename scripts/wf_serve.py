#!/usr/bin/env python3
"""wf_serve — serving front-door CLI (``windflow_tpu/serving``).

The operator's tool for the serving plane: probe a front door without the
compute plane, read a serving run's tenant/SWAP state from its monitoring
artifacts, and drive a zero-downtime graph hot-swap over the wire.

Subcommands:

- ``serve``    — a standalone WFS1 frame sink on ``--listen``: accepts
  clients, decodes record frames (magic + resync discipline, per-tenant
  seq dedup), and prints per-tenant record/byte totals on SIGINT/EOS.
  No JAX, no numpy: this is the producer-side debugging tool — point a
  client at it and see exactly what a ``ServingRuntime`` would ingest::

      python scripts/wf_serve.py serve --listen tcp://0.0.0.0:9910

- ``status``   — one-shot read of a serving run's monitoring directory
  (``snapshot.json``): live graph, swap counters, framing health, and the
  per-tenant admit/shed table with tenant-labelled SLO states.
- ``swap``     — send a ``swap`` control frame to a LIVE serving endpoint:
  the runtime cuts over to the named registered graph at the next batch
  boundary (``ServingRuntime.register_graph`` names the candidates)::

      python scripts/wf_serve.py swap --endpoint tcp://host:9910 --graph v2

- ``selftest`` — one-shot client→server loopback on an ephemeral endpoint:
  two tenants, interleaved garbage and a duplicated seq, then EOS — proves
  framing encode/decode, resync, and dedup end to end.  CI runs this under
  a poisoned-JAX PYTHONPATH.

Stdlib only (``windflow_tpu/serving/{framing,tenants}.py`` are loaded by
file path — the ``wf_state.py`` convention), so every subcommand runs on a
box without JAX or numpy installed.

Exit codes: 0 = served/rendered/swapped/selftest passed, 2 =
missing/unreadable inputs, bad endpoint, or a failed selftest
(``scripts/ci.sh`` pins the contract).
"""

import argparse
import importlib.util
import json
import os
import signal
import socket
import sys
import threading
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_STATE = {0: "ok", 1: "warn", 2: "page"}


def _load_serving(names=("framing", "tenants")):
    """Load the serving helper modules by file path under a synthetic
    package — no windflow_tpu package import, no JAX/numpy (the wf_slo.py
    loader, pointed at ``windflow_tpu/serving``)."""
    srv = os.path.join(REPO, "windflow_tpu", "serving")
    pkg = sys.modules.get("wf_serving")
    if pkg is None:
        pkg = types.ModuleType("wf_serving")
        pkg.__path__ = [srv]
        sys.modules["wf_serving"] = pkg
    for name in names:
        if f"wf_serving.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_serving.{name}", os.path.join(srv, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_serving.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return sys.modules["wf_serving.framing"]


# ------------------------------------------------------------ serve


class _FrameSink:
    """A minimal WFS1 receiver: one decoder per client, per-tenant seq
    dedup, per-tenant record/byte totals.  The producer-side contract
    half of ``serving/sources.py::SocketSource`` — same framing, same
    dedup rule, no compute plane behind it."""

    def __init__(self, framing, endpoint):
        self.framing = framing
        kind, host, port = framing.parse_endpoint(endpoint)
        if kind == "unix":
            if os.path.exists(host):
                os.unlink(host)
            self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._srv.bind(host)
            self.endpoint = endpoint
        else:
            self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host, port))
            bhost, bport = self._srv.getsockname()[:2]
            self.endpoint = f"tcp://{bhost}:{bport}"
        self._srv.listen(8)
        self._srv.settimeout(0.2)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.tenants = {}               # tid -> {frames, records_bytes, dup}
        self.frames_torn = 0
        self.swaps = []                 # graph labels seen in swap frames
        self.eos = threading.Event()
        self._last_seq = {}
        self._threads = []

    def _account(self, meta, blob):
        tid = str(meta.get("tenant", self.framing.DEFAULT_TENANT))
        kind = meta.get("kind", self.framing.KIND_DATA)
        with self._lock:
            row = self.tenants.setdefault(
                tid, {"frames": 0, "records_bytes": 0, "dup": 0})
            if kind == self.framing.KIND_SWAP:
                self.swaps.append(meta.get("graph"))
                return
            seq = int(meta.get("seq", 0))
            if seq <= self._last_seq.get(tid, -1):
                row["dup"] += 1
                return
            self._last_seq[tid] = seq
            if kind == self.framing.KIND_EOS:
                self.eos.set()
                return
            row["frames"] += 1
            row["records_bytes"] += len(blob)

    def _client(self, conn):
        dec = self.framing.RecordFrameDecoder()
        conn.settimeout(0.2)
        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:
                    break
                for meta, blob in dec.feed(data):
                    self._account(meta, blob)
                with self._lock:
                    self.frames_torn += dec.frames_torn
                    dec.frames_torn = 0
        finally:
            conn.close()

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._client, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def start(self):
        t = threading.Thread(target=self._accept, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)

    def report(self):
        with self._lock:
            return {"endpoint": self.endpoint, "frames_torn": self.frames_torn,
                    "swaps": list(self.swaps),
                    "tenants": {t: dict(r) for t, r in self.tenants.items()}}


def cmd_serve(args) -> int:
    framing = _load_serving()
    try:
        sink = _FrameSink(framing, args.listen)
    except (ValueError, OSError) as e:
        print(f"wf_serve: cannot listen on {args.listen!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    sink.start()
    stop = []
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.append(1))
    print(f"wf_serve: frame sink on {sink.endpoint} "
          f"(WFS1 frames; ctrl-C or an eos frame to finish)", flush=True)
    while not stop and not sink.eos.is_set():
        time.sleep(0.2)
    sink.stop()
    rep = sink.report()
    for tid in sorted(rep["tenants"]):
        row = rep["tenants"][tid]
        print(f"  tenant {tid}: {row['frames']} frame(s), "
              f"{row['records_bytes']} record byte(s), {row['dup']} dup")
    print(f"  torn: {rep['frames_torn']}  swap requests: "
          f"{rep['swaps'] or '—'}")
    return 0


# ------------------------------------------------------------ status


def cmd_status(args) -> int:
    path = os.path.join(args.monitoring_dir, "snapshot.json")
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"wf_serve: cannot read {path!r}: {type(e).__name__}: {e}\n"
              f"(run a ServingRuntime with monitoring on, or point "
              f"--monitoring-dir at its out_dir)", file=sys.stderr)
        return 2
    srv = snap.get("serving") or {}
    if args.json:
        print(json.dumps(srv, indent=2, sort_keys=True))
        return 0
    if not srv:
        print(f"wf_serve: {args.monitoring_dir!r} has no serving section "
              f"(not a ServingRuntime run?)", file=sys.stderr)
        return 2
    print(f"serving @ {args.monitoring_dir!r}  graph={srv.get('graph', '?')}"
          f"  swaps={srv.get('swaps_applied', 0)} "
          f"(+{srv.get('swaps_rejected', 0)} rejected)"
          + (f"  endpoint={srv['endpoint']}" if srv.get("endpoint") else ""))
    if srv.get("frames_decoded") is not None:
        print(f"  frames: {srv.get('frames_decoded', 0):g} decoded  "
              f"{srv.get('frames_torn', 0):g} torn  "
              f"{srv.get('frames_dup', 0):g} dup  "
              f"clients={srv.get('clients_seen', 0):g}")
    # worst tenant-labelled SLO state per tenant (the wf_top join)
    worst = {}
    for name, row in (snap.get("slo") or {}).items():
        if isinstance(row, dict) and row.get("tenant") is not None:
            code = row.get("code", 0) or 0
            if code >= worst.get(row["tenant"], (-1, ""))[0]:
                worst[row["tenant"]] = (code, name)
    for tid in sorted(srv.get("tenants") or {}):
        row = srv["tenants"][tid]
        code, slo_name = worst.get(tid, (None, None))
        state = _STATE.get(code, "—") if code is not None else "—"
        rate = row.get("rate")
        # tenant latency joined to the tenant-labelled SLO on the same
        # line: the p99 the latency spec reads, next to the state it drove
        lat = ""
        if row.get("e2e_samples"):
            lat = (f"p50={row.get('e2e_p50_ms', 0):g}ms "
                   f"p95={row.get('e2e_p95_ms', 0):g}ms "
                   f"p99={row.get('e2e_p99_ms', 0):g}ms ")
            ex = row.get("e2e_p99_exemplar")
            if isinstance(ex, int):
                lat += f"p99_trace={ex:#x} "
        print(f"  tenant {tid:<14} offered={row.get('offered', 0):g} "
              f"admitted={row.get('admitted', 0):g} "
              f"shed={row.get('shed', 0):g} "
              f"shed_tuples={row.get('shed_tuples', 0):g} "
              f"rate={f'{rate:g}' if rate is not None else 'unlim'}  "
              f"{lat}"
              f"slo={state}{f' ({slo_name})' if slo_name else ''}")
    return 0


# ------------------------------------------------------------ swap


def cmd_swap(args) -> int:
    framing = _load_serving()
    try:
        client = framing.RecordClient(args.endpoint)
        client.send_swap(args.graph)
        client.close()
    except (ValueError, OSError) as e:
        print(f"wf_serve: cannot send swap to {args.endpoint!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    print(f"wf_serve: swap -> {args.graph!r} sent to {args.endpoint} "
          f"(applies at the runtime's next batch boundary; unregistered "
          f"labels count as swaps_rejected)")
    return 0


# ------------------------------------------------------------ selftest


def cmd_selftest(args) -> int:
    """Client→server loopback on an ephemeral endpoint: two tenants,
    interleaved garbage bytes and one duplicated seq, then EOS.  Pins the
    wire contract ``SocketSource`` relies on — without JAX or numpy."""
    framing = _load_serving()
    sink = _FrameSink(framing, "tcp://127.0.0.1:0")
    sink.start()
    try:
        client = framing.RecordClient(sink.endpoint)
        rec_a = bytes(range(24)) * 4          # fake fixed-width rows
        rec_b = bytes(reversed(range(24))) * 2
        client.send(rec_a, tenant="a")
        client.send_garbage(b"NOISE " * 7)    # torn → resync at next magic
        client.send(rec_b, tenant="b")
        client.send(rec_a, tenant="a", seq=0)  # duplicate seq → dedup
        client.send(rec_b, tenant="b")
        client.send_eos("a")
        client.close()
        deadline = time.time() + 5.0
        while time.time() < deadline and not sink.eos.is_set():
            time.sleep(0.02)
        rep = sink.report()
    finally:
        sink.stop()
    ok = (sink.eos.is_set()
          and rep["tenants"].get("a", {}).get("frames") == 1
          and rep["tenants"].get("a", {}).get("dup") == 1
          and rep["tenants"].get("b", {}).get("frames") == 2
          and rep["tenants"].get("a", {}).get("records_bytes") == len(rec_a)
          and rep["tenants"].get("b", {}).get("records_bytes")
          == 2 * len(rec_b)
          and rep["frames_torn"] >= 1)
    if args.json:
        print(json.dumps({"ok": ok, **rep}, indent=2, sort_keys=True))
    else:
        print(f"wf_serve selftest: {'OK' if ok else 'FAILED'} — "
              f"{json.dumps(rep['tenants'], sort_keys=True)} "
              f"torn={rep['frames_torn']}")
    return 0 if ok else 2


# ------------------------------------------------------------ main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_serve",
        description="serving front-door CLI: standalone frame sink, "
                    "serving-run status, wire-driven graph hot-swap, "
                    "loopback selftest")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("serve", help="standalone WFS1 frame sink (no JAX)")
    p.add_argument("--listen", default="tcp://127.0.0.1:0",
                   help="endpoint to bind (tcp://HOST:PORT, port 0 = "
                        "ephemeral, or unix:///path.sock)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("status",
                       help="render a serving run's tenant/swap state")
    p.add_argument("--monitoring-dir", default="wf_monitoring",
                   help="the ServingRuntime's monitoring out_dir")
    p.add_argument("--json", action="store_true",
                   help="emit the raw serving section as JSON")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("swap",
                       help="send a graph hot-swap control frame to a "
                            "live serving endpoint")
    p.add_argument("--endpoint", required=True,
                   help="the ServingRuntime's SocketSource endpoint")
    p.add_argument("--graph", required=True,
                   help="registered graph label to cut over to")
    p.set_defaults(fn=cmd_swap)

    p = sub.add_parser("selftest",
                       help="loopback framing/dedup/resync selftest "
                            "(ephemeral endpoint, no JAX/numpy)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_selftest)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
