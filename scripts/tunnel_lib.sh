# Shared tunnel-liveness helpers, sourced by the probe runners and watcher.
#
# alive: one short device round trip (timeout 90 — platform init over the
#   tunnel can take 60-90 s; the watcher's historical probe uses the same
#   budget). Returns nonzero when the link is down.
# ok_or_bail <rc> <log>: cheap gating policy — only when the PREVIOUS command
#   failed do we spend an alive round trip to distinguish "probe bug" from
#   "tunnel died"; a probe that just succeeded proves the link was up seconds
#   ago. On a dead link, logs TUNNEL DIED and exits 3 (callers must check).

alive() {
  timeout 90 python -c "
import jax, jax.numpy as jnp
x = jax.device_put(jnp.ones((1024,), jnp.float32))
assert float((x*2).sum()) == 2048.0" >/dev/null 2>&1
}

ok_or_bail() {
  local rc="$1" log="$2"
  [ "$rc" -eq 0 ] && return 0
  if ! alive; then
    echo "TUNNEL DIED mid-run $(date -u +%FT%TZ) — aborting remaining probes" >> "$log"
    exit 3
  fi
  return 0          # probe failed but link is up: a real (reportable) failure
}
