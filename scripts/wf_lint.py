#!/usr/bin/env python3
"""wf_lint — run the framework invariant linter over this repository.

Stdlib only (the linter module is loaded by file path, bypassing the
``windflow_tpu`` package ``__init__`` and its JAX imports), so this works as
a pre-commit hook on any box:

    python scripts/wf_lint.py                    # text report
    python scripts/wf_lint.py --format=json      # machine-readable
    python scripts/wf_lint.py --update-baseline  # accept current findings

Exit codes: 0 = clean (no non-baselined findings), 1 = findings, 2 =
internal error (the linter itself failed — never confuse a broken gate
with a clean one).

Baseline: ``windflow_tpu/analysis/baseline.json`` suppresses pre-existing
findings (override with ``--baseline`` or the ``WF_LINT_BASELINE`` env var);
``--update-baseline`` rewrites it from the current findings so the gate
fails only on regressions from here on.
"""

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint():
    """Load analysis/lint.py directly — no package import, no JAX."""
    path = os.path.join(REPO, "windflow_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("wf_analysis_lint", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass field resolution looks the module up in sys.modules mid-exec
    sys.modules["wf_analysis_lint"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_lint", description="windflow_tpu framework invariant linter")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--root", default=REPO,
                    help="repository root to lint (default: this repo)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file overriding analysis/baseline.json "
                         "(WF_LINT_BASELINE env does the same)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    args = ap.parse_args(argv)

    try:
        lint = _load_lint()
        cfg = lint.LintConfig(root=args.root)
        if args.baseline:
            # resolve against the INVOKER's cwd, not the lint root
            os.environ["WF_LINT_BASELINE"] = os.path.abspath(args.baseline)
        findings = lint.run_lint(cfg=cfg)
        bpath = lint.baseline_path(cfg)
        if args.update_baseline:
            lint.save_baseline(bpath, findings)
            print(f"wf_lint: wrote {len(findings)} finding(s) to {bpath}")
            return 0
        if args.no_baseline:
            fresh, suppressed = findings, []
        else:
            fresh, suppressed = lint.split_baseline(cfg, findings)
    except Exception as e:  # noqa: BLE001 — a broken linter must exit 2,
        #                     never masquerade as a clean (0) or dirty (1) run
        print(f"wf_lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({
            "findings": [x.to_dict() for x in fresh],
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        for x in fresh:
            print(x.render())
        print(f"wf_lint: {len(fresh)} finding(s) "
              f"({len(suppressed)} baselined)")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
