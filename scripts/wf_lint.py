#!/usr/bin/env python3
"""wf_lint — run the framework invariant linter over this repository.

Stdlib only (the linter module is loaded by file path, bypassing the
``windflow_tpu`` package ``__init__`` and its JAX imports), so this works as
a pre-commit hook on any box:

    python scripts/wf_lint.py                    # text report
    python scripts/wf_lint.py --format=json      # machine-readable
    python scripts/wf_lint.py --update-baseline  # accept current findings
    python scripts/wf_lint.py --select WF26x     # only the concurrency pass
    python scripts/wf_lint.py --ignore WF230     # everything but one code
    python scripts/wf_lint.py --explain WF261    # what a code means

``--select``/``--ignore`` take comma-separated codes; a trailing ``x``
matches a family (``WF26x`` = WF260..WF269, ``WF2x`` = everything).
Filtering happens BEFORE the baseline split, so a selected run behaves
exactly like the gate restricted to those codes — handy for triaging a new
rule family in isolation (scripts/ci.sh always runs the full set).

Exit codes: 0 = clean (no non-baselined findings), 1 = findings, 2 =
internal error (the linter itself failed — never confuse a broken gate
with a clean one; an unknown code in --select/--ignore/--explain is a
broken invocation, also 2).

Baseline: ``windflow_tpu/analysis/baseline.json`` suppresses pre-existing
findings (override with ``--baseline`` or the ``WF_LINT_BASELINE`` env var);
``--update-baseline`` rewrites it from the current findings so the gate
fails only on regressions from here on.
"""

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint():
    """Load analysis/lint.py directly — no package import, no JAX."""
    path = os.path.join(REPO, "windflow_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("wf_analysis_lint", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass field resolution looks the module up in sys.modules mid-exec
    sys.modules["wf_analysis_lint"] = mod
    spec.loader.exec_module(mod)
    return mod


def _parse_codes(lint, text: str):
    """``--select``/``--ignore`` tokens -> concrete code set.  A trailing
    ``x`` matches a family by prefix (``WF26x``, ``WF2x``) — the prefix
    must be ``WF`` plus at least one digit, or a typo like ``x`` would
    match EVERYTHING and (under --ignore) silently disable the whole gate;
    exact tokens must name a registered rule (silently selecting nothing
    would turn the gate into a no-op — both are broken invocations,
    exit 2)."""
    import re
    codes = set()
    for tok in [t.strip() for t in text.split(",") if t.strip()]:
        if re.fullmatch(r"WF\d+x", tok):
            fam = [c for c in lint.RULES if c.startswith(tok[:-1])]
            if not fam:
                raise ValueError(f"unknown rule family {tok!r}")
            codes.update(fam)
        elif tok in lint.RULES:
            codes.add(tok)
        else:
            raise ValueError(
                f"unknown rule code {tok!r} (see --explain, or the RULES "
                f"table in windflow_tpu/analysis/lint.py)")
    return codes


def _explain(lint, code: str) -> int:
    if code not in lint.RULES:
        print(f"wf_lint: unknown rule code {code!r}; registered codes: "
              f"{', '.join(sorted(lint.RULES))}", file=sys.stderr)
        return 2
    severity, summary = lint.RULES[code]
    print(f"{code} [{severity}] {summary}")
    # the long-form story lives in the implementing module's docstring —
    # print the matching table row block for context.  WF26x lives in
    # concurrency.py; WF30x in progcheck.py (read via ast — progcheck
    # imports JAX, and --explain must work on a box without it)
    if code.startswith("WF26"):
        doc = lint.concurrency_module().__doc__ or ""
    elif code.startswith("WF30"):
        doc = lint.progcheck_doc()
    else:
        doc = lint.__doc__ or ""
    in_block = False
    for line in doc.splitlines():
        if line.strip().startswith(code):
            in_block = True
        elif in_block and (line.strip().startswith("WF")
                           or line.strip().startswith("=====")):
            break
        if in_block:
            print(line)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_lint", description="windflow_tpu framework invariant linter")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--root", default=REPO,
                    help="repository root to lint (default: this repo)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file overriding analysis/baseline.json "
                         "(WF_LINT_BASELINE env does the same)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--select", default=None, metavar="CODES",
                    help="comma-separated codes/families to run in "
                         "isolation (WF230 or WF26x); others are dropped "
                         "before the baseline split")
    ap.add_argument("--ignore", default=None, metavar="CODES",
                    help="comma-separated codes/families to drop")
    ap.add_argument("--explain", default=None, metavar="WFnnn",
                    help="print what a rule code means and exit")
    args = ap.parse_args(argv)

    try:
        lint = _load_lint()
        if args.explain:
            return _explain(lint, args.explain)
        if args.update_baseline and (args.select or args.ignore):
            # a filtered run sees a subset — banking it would ERASE the
            # suppressions for every other code (ratchet corruption);
            # checked BEFORE the (multi-second) lint run
            print("wf_lint: refusing --update-baseline with "
                  "--select/--ignore (a partial baseline would drop "
                  "the other codes' suppressions)", file=sys.stderr)
            return 2
        # validate the code filters up front: a typo'd code must fail fast
        # as a broken invocation, not after a full repo scan
        keep = _parse_codes(lint, args.select) if args.select else None
        drop = _parse_codes(lint, args.ignore) if args.ignore else None
        cfg = lint.LintConfig(root=args.root)
        wf26x = {c for c in lint.RULES if c.startswith("WF26")}
        if (keep is not None and not (keep & wf26x)) \
                or (drop is not None and wf26x <= drop):
            # the run cannot surface any WF26x finding (none selected, or
            # the whole family ignored): skip the whole-repo concurrency
            # index/inference instead of discarding its findings
            cfg.concurrency = False
        if args.baseline:
            # resolve against the INVOKER's cwd, not the lint root
            os.environ["WF_LINT_BASELINE"] = os.path.abspath(args.baseline)
        findings = lint.run_lint(cfg=cfg)
        if keep is not None:
            findings = [x for x in findings if x.code in keep]
        if drop is not None:
            findings = [x for x in findings if x.code not in drop]
        bpath = lint.baseline_path(cfg)
        if args.update_baseline:
            lint.save_baseline(bpath, findings)
            print(f"wf_lint: wrote {len(findings)} finding(s) to {bpath}")
            return 0
        if args.no_baseline:
            fresh, suppressed = findings, []
        else:
            fresh, suppressed = lint.split_baseline(cfg, findings)
    except Exception as e:  # noqa: BLE001 — a broken linter must exit 2,
        #                     never masquerade as a clean (0) or dirty (1) run
        print(f"wf_lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({
            "findings": [x.to_dict() for x in fresh],
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        for x in fresh:
            print(x.render())
        print(f"wf_lint: {len(fresh)} finding(s) "
              f"({len(suppressed)} baselined)")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
