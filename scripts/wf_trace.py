#!/usr/bin/env python3
"""wf_trace — flight-recorder diagnosis CLI.

Converts a tracing run's artifacts into a Chrome trace-event file (Perfetto /
chrome://tracing loadable — drop it next to an ``xprof_trace`` capture) and,
with ``--report``, prints the critical-path breakdown: per-stage service vs
SPSC queue wait vs governor throttle vs supervised restart/shed attribution,
a per-tenant wire-to-sink section when the flight records carry serving
ingest extras (wire vs queue vs service vs e2e per tenant, shed-at-admission
counts, the slowest request's segment verdict), plus a drill-down of the
slowest traced batches and the p99 exemplar from the metrics snapshot.

Inputs (produced by a run with ``trace=``/``WF_TRACE`` on; the journal and
snapshot pieces appear when ``monitoring=``/``WF_MONITORING`` ran too):

- ``<trace-dir>/flight.jsonl`` + ``meta.json``  — the flight recorder dump
- ``<monitoring-dir>/events.jsonl``             — the event journal
- ``<monitoring-dir>/snapshot.json``            — latency histograms/exemplars

Stdlib only (``observability/tracing.py`` and ``journal.py`` are loaded by
file path, bypassing the package ``__init__`` and its JAX imports), so this
works on any box the artifacts were copied to:

    python scripts/wf_trace.py --trace-dir wf_trace
    python scripts/wf_trace.py --trace-dir wf_trace \\
        --monitoring-dir wf_monitoring --report

Exit codes: 0 = trace written, 2 = missing/unreadable inputs or usage error.
"""

import argparse
import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tracing():
    """Load observability/tracing.py (and the journal + device_health
    modules: the relative import, and THE shared snapshot loader of
    wf_state/wf_trace/wf_health) by file path under a synthetic package —
    no windflow_tpu package import, no JAX."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in ("journal", "device_health", "tracing"):
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return sys.modules["wf_obs.tracing"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_trace",
        description="windflow_tpu flight-recorder diagnosis CLI")
    ap.add_argument("--trace-dir", default="wf_trace",
                    help="Tracer output directory (flight.jsonl + meta.json)")
    ap.add_argument("--monitoring-dir", default=None,
                    help="monitoring output directory (events.jsonl + "
                         "snapshot.json) for journal correlation; default: "
                         "./wf_monitoring when it exists")
    ap.add_argument("--out", default=None,
                    help="Chrome trace-event output path "
                         "(default: <trace-dir>/trace.json)")
    ap.add_argument("--report", action="store_true",
                    help="print the critical-path breakdown and slowest-"
                         "batch drill-down")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest batches to drill into (default 5)")
    args = ap.parse_args(argv)

    try:
        tracing = _load_tracing()
    except (OSError, ImportError, SyntaxError) as e:
        # the 0/2 contract covers the helper modules too (the wf_state.py
        # convention): an artifacts-only box without the windflow_tpu tree
        # beside this script gets guidance, not a traceback
        print(f"wf_trace: cannot load observability helpers from "
              f"{REPO!r}: {type(e).__name__}: {e}\n"
              f"(keep scripts/wf_trace.py next to its windflow_tpu tree — "
              f"it loads tracing.py/journal.py/device_health.py by file "
              f"path)", file=sys.stderr)
        return 2
    try:
        records, meta = tracing.load_flight(args.trace_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"wf_trace: cannot load flight recorder from "
              f"{args.trace_dir!r}: {type(e).__name__}: {e}\n"
              f"(run with trace=/the WF_TRACE env flag set to produce "
              f"flight.jsonl + meta.json)", file=sys.stderr)
        return 2

    mon_dir = args.monitoring_dir
    if mon_dir is None and os.path.isdir("wf_monitoring"):
        mon_dir = "wf_monitoring"
    journal_events, snapshot = [], None
    if mon_dir:
        # the shared loader (device_health.py, loaded alongside tracing):
        # torn-tolerant, one parser for all three CLIs
        dh = sys.modules["wf_obs.device_health"]
        journal_events = dh.load_journal(mon_dir)
        try:
            snapshot, _series = dh.load_snapshots(mon_dir)
        except (OSError, ValueError):
            snapshot = None                # trace-only run: no snapshots

    out_path = args.out or os.path.join(args.trace_dir, "trace.json")
    trace = tracing.to_chrome_trace(records, journal_events, meta)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    n_spans = sum(1 for e in trace["traceEvents"] if e["ph"] == "B")
    print(f"wf_trace: wrote {out_path} — {len(trace['traceEvents'])} events "
          f"({n_spans} spans) from {len(records)} flight records, "
          f"{len(journal_events)} journal events "
          f"(load in Perfetto / chrome://tracing)")
    if trace["otherData"].get("dropped_begins"):
        print(f"wf_trace: note: {trace['otherData']['dropped_begins']} "
              f"unmatched begin record(s) dropped (ring wrap or a crash "
              f"without supervision)")
    if args.report:
        print()
        print(tracing.critical_path_report(
            records, journal_events, snapshot, meta, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
