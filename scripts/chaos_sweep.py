#!/usr/bin/env python
"""Chaos sweep: run N seeded fault plans through all three drivers and report
any divergence from the fault-free baseline.

For each seed a probabilistic FaultPlan (errors on source.next / chain.step /
sink.consume for the supervised drivers, stalls on queue.stall for the
threaded driver) is injected via runtime/faults.py; the run's outputs must be
byte-identical to the fault-free oracle (exactly-once under injection).
Exit code 0 = no divergence, 1 = at least one.

--controller additionally runs every driver with the adaptive control plane
active (deterministic positional admission on the supervised drivers — shed
decisions are part of the replayed stream, so faulted runs must still match
the fault-free controlled baseline byte-for-byte; backpressure governor on
the threaded driver). Controller + injection must neither diverge nor
livelock the supervisor's backoff.

--dispatch K runs every CHAOS run with scan dispatch (K-fused push_many)
while the fault-free baselines stay per-batch — asserting the dispatch
byte-identity claim and the recovery machinery in one sweep. The graph_det
driver (DETERMINISTIC merge) keeps the Ordering_Node's async counts
readback in every sweep, dispatch or not.

--shards N runs the two SUPERVISED drivers (pipeline + graph) through the
shard-local supervision layer (N ShardSupervisor units) and widens each
seed's plan with shard-kill and torn reshard-handoff injection; the
fault-free baselines stay UNSHARDED, so every seed asserts shard-count
invariance AND shard-local recovery byte-identity at once. The sharded
pipeline run additionally carries a mid-stream N -> 2N live reshard.
(--shards excludes --dispatch on the supervised drivers: WF115.)

--remediate closes the loop: the supervised PIPELINE runs (baseline AND
chaos) carry barrier remediation (``remediation=True`` + deterministic
positional admission) — decisions are part of the replayed stream, so the
faulted remediated runs must match the remediated baseline byte-for-byte.
It then adds one LIVE threaded leg under queue.stall chaos riding the full
self-driving loop — OK -> PAGE (drop_ratio burn) -> shed_harder actuation ->
recovery back to OK — asserting the loop shape and that the incident bundle
recorded the actions (lossy by design: admission sheds, so THIS leg asserts
recovery, not byte-identity).

--serve runs ONLY the serving closed-loop legs (one per seed): a
ServingRuntime ingesting two tenants over a real loopback socket, with a
seeded peer kill mid-stream (abrupt close, torn frame), garbage-byte
injection, a full reconnect re-send (the dedup overlap), and a live
graph hot-swap to a registered twin graph mid-stream — the outputs must
be byte-identical to a RecordSource oracle fed the same chunks, with
zero dropped committed tuples, >= 1 torn frame resync'd and >= 1
duplicate frame deduped (the peer-kill-degrades-to-replay contract).

    JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --seeds 5 --total 400
    JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --seeds 5 --controller
    JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --seeds 5 --dispatch 4
    JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --seeds 5 --shards 4
    JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --seeds 3 --remediate
    JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --seeds 3 --serve
"""

import argparse
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np                                        # noqa: E402
import jax.numpy as jnp                                   # noqa: E402

import windflow_tpu as wf                                 # noqa: E402
from windflow_tpu.basic import win_type_t                 # noqa: E402
from windflow_tpu.operators.window import WindowSpec      # noqa: E402
from windflow_tpu.runtime import faults as faults_mod     # noqa: E402
from windflow_tpu.runtime.faults import (FaultInjector,   # noqa: E402
                                         FaultPlan, FaultSpec)
from windflow_tpu.runtime.pipegraph import PipeGraph      # noqa: E402
from windflow_tpu.runtime.supervisor import SupervisedPipeline  # noqa: E402
from windflow_tpu.runtime.threaded import ThreadedPipeline      # noqa: E402
from windflow_tpu.control import ControlConfig                  # noqa: E402


def sup_control(batch):
    # deterministic positional bucket: ~80% admitted, replay-stable
    return ControlConfig(autotune=False, backpressure=False, admission=True,
                         refill_per_batch=0.8 * batch, burst_tuples=2 * batch)


def thr_control():
    # governor only: throttling delays, never drops — results must not change
    return ControlConfig(autotune=False, backpressure=True,
                         high_watermark=0.5, low_watermark=0.25)


def collect(acc):
    def cb(view):
        if view is None:
            return
        acc.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))
    return cb


def run_pipeline(total, batch, faults=None, controller=False, dispatch=False,
                 shards=0, remediate=False):
    got = []
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=total, num_keys=4)
    op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(10, 10, win_type_t.TB), num_keys=4)
    SupervisedPipeline(src, [op], wf.Sink(collect(got)), batch_size=batch,
                       checkpoint_every=3, max_restarts=8,
                       backoff_base=0.001, backoff_cap=0.01,
                       faults=faults, dispatch=dispatch,
                       shards=shards or 1,
                       # sharded runs also cross a live N -> 2N reshard at
                       # the first barrier past 1/3 of the stream — chaos
                       # seeds then hit shard kills AND torn handoffs
                       reshard=({"new_shards": shards * 2,
                                 "at_pos": max(1, total // batch // 3)}
                                if shards else False),
                       # --remediate: barrier remediation over the owned
                       # actuators (admission always; reshard when sharded)
                       # — decisions are replayed state, so byte-identity
                       # against the remediated baseline still holds
                       remediation=True if remediate else None,
                       control=(sup_control(batch)
                                if (controller or remediate) else False)
                       ).run()
    return sorted(got)


def run_graph(total, batch, faults=None, controller=False, dispatch=False,
              mode=None, shards=0):
    from windflow_tpu.basic import Mode
    got = []
    g = PipeGraph("sweep", batch_size=batch,
                  mode=mode or Mode.DEFAULT, dispatch=dispatch)
    a = g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                               total=total, num_keys=3, name="a"))
    b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                               total=total // 2, num_keys=3, name="b"))
    (a.merge(b)
     .add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                     WindowSpec(12, 12, win_type_t.CB), num_keys=3))
     .add_sink(wf.Sink(collect(got))))
    g.run_supervised(checkpoint_every=3, max_restarts=8,
                     backoff_base=0.001, backoff_cap=0.01, faults=faults,
                     shards=shards or 1,
                     # hermetic: the graph runs never reshard in the sweep —
                     # a caller's WF_RESHARD must not diverge them from the
                     # unsharded baselines (run_pipeline pins its own plan)
                     reshard=False,
                     control=sup_control(batch) if controller else False)
    return sorted(got)


def run_graph_det(total, batch, faults=None, controller=False,
                  dispatch=False, shards=0):
    # DETERMINISTIC merge: every root push drives the Ordering_Node's
    # async [n_released, n_kept] readback — the sync-free hot path under
    # chaos (and under fused dispatch when --dispatch is on)
    from windflow_tpu.basic import Mode
    return run_graph(total, batch, faults=faults, controller=controller,
                     dispatch=dispatch, mode=Mode.DETERMINISTIC,
                     shards=shards)


def run_threaded(total, batch, faults=None, controller=False,
                 dispatch=False):
    got = []
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total)
    ThreadedPipeline(src, [[wf.Map(lambda t: {"v": t.v * 3})],
                           [wf.Map(lambda t: {"v": t.v + 1})]],
                     wf.Sink(lambda v: got.extend(
                         zip(v["id"].tolist(),
                             np.asarray(v["payload"]["v"]).tolist()))
                         if v is not None else None),
                     batch_size=batch, pin=False, heartbeat_timeout=0.25,
                     faults=faults, dispatch=dispatch,
                     control=thr_control() if controller else False).run()
    return sorted(got)


def run_closed_loop(seed):
    """The headline --remediate acceptance: a LIVE threaded run under
    queue.stall chaos rides the full self-driving loop — OK -> PAGE
    (drop_ratio burn) -> shed_harder actuation -> recovery back to OK —
    with the incident bundle recording the actions the page triggered.
    Lossy by design (admission sheds during the flood), so this leg
    asserts the loop shape, not byte-identity.  Returns (problems,
    n_applies, n_faults)."""
    import json
    import shutil
    import tempfile

    from windflow_tpu.control import RemediationAction, RemediationPolicy
    from windflow_tpu.observability import MonitoringConfig

    mon_dir = tempfile.mkdtemp(prefix="wf_chaos_remediate_")
    batch, total = 32, 6000
    got = []

    def sink(view):
        # host-side pacing (the sink is a plain callback, never traced):
        # ~4ms/batch keeps the run alive long past the bounded stall burst,
        # so the burn windows get clean post-incident ticks to decay over
        if view is not None:
            got.extend(view["id"].tolist())
        time.sleep(0.004)

    # the admission rate is astronomically high: shed_harder's actuation is
    # REAL (the setpoint halves, journaled, gauged) but never actually
    # sheds, so the closed-loop leg also asserts zero tuple loss
    policy = RemediationPolicy((RemediationAction(
        name="shed_harder", slo="latency", actuator="admission_rate",
        factor=0.5, floor=1.0, window=2, max_applies=2),))
    mon = MonitoringConfig(
        slo=json.dumps([{"name": "latency", "signal": "e2e_p99_ms",
                         "target": 150.0, "objective": 0.5,
                         "fast_window": 2, "slow_window": 4,
                         "warn_burn": 0.5, "page_burn": 1.0}]),
        remediation=policy, interval_s=0.05, remediation_cooldown_s=0.05,
        out_dir=mon_dir)
    # a bounded burst of queue stalls: each holds a ring op ~0.5s, so the
    # delayed batches blow the per-tick e2e p99 past target (OK -> PAGE);
    # max_fires bounds the incident, so the tail of the run recovers
    inj = FaultInjector(FaultPlan([FaultSpec("queue.stall", kind="stall",
                                             p=0.25, stall_s=0.5,
                                             max_fires=4)], seed=seed))
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=total, num_keys=4)
    ThreadedPipeline(src, [[wf.Map(lambda t: {"v": t.v + 1.0})]],
                     wf.Sink(sink),
                     batch_size=batch, pin=False, heartbeat_timeout=0.25,
                     faults=inj,
                     control=ControlConfig(autotune=False,
                                           backpressure=False,
                                           admission=True, rate_tps=1e9),
                     monitoring=mon).run()

    snaps = [json.loads(line)
             for line in open(os.path.join(mon_dir, "snapshots.jsonl"))]
    events = [json.loads(line)
              for line in open(os.path.join(mon_dir, "events.jsonl"))]
    applies = [e for e in events if e.get("event") == "remediation_apply"]
    paged = any((s.get("slo") or {}).get("latency", {}).get("state")
                == "page" for s in snaps)
    final = (snaps[-1].get("slo") or {}).get("latency", {}).get("state")
    inc_dir = os.path.join(mon_dir, "incidents")
    bundles = sorted(os.listdir(inc_dir)) if os.path.isdir(inc_dir) else []
    with_rem = [b for b in bundles if os.path.exists(
        os.path.join(inc_dir, b, "remediation.json"))]
    problems = []
    if not paged:
        problems.append("the latency SLO never paged")
    if not applies:
        problems.append("no remediation_apply journaled")
    if final != "ok":
        problems.append(f"final state {final!r} — did not recover to ok")
    if not bundles:
        problems.append("no incident bundle captured for the page")
    elif not with_rem:
        problems.append("no incident bundle recorded remediation.json")
    if sorted(got) != list(range(total)):
        problems.append(f"tuple loss: {len(got)}/{total} delivered")
    shutil.rmtree(mon_dir, ignore_errors=True)
    return problems, len(applies), len(inj.fired)


def run_serve_loop(seed, total=2000, chunk=50):
    """The --serve acceptance: a ServingRuntime fed two tenants over a
    real loopback socket, with a seeded mid-stream peer kill (abrupt
    close), garbage injection, a full re-send on reconnect (the dedup
    overlap), and a live hot-swap to a registered twin graph — outputs
    must be byte-identical to a RecordSource oracle over the same chunks.
    Returns (problems, counters)."""
    import json
    import shutil
    import tempfile

    from windflow_tpu.serving import (RecordClient, ServingRuntime,
                                      SocketSource)

    rng = np.random.RandomState(seed)
    dt = np.dtype([("key", np.int32), ("ts", np.int64), ("v", np.float32)])
    recs = np.zeros(total, dtype=dt)
    recs["key"] = rng.randint(0, 8, total)
    recs["ts"] = np.arange(total)
    recs["v"] = rng.rand(total).astype(np.float32)
    chunks = [recs[i:i + chunk] for i in range(0, total, chunk)]
    # even chunks ride tenant "a", odd ones "b" — both unlimited, so the
    # byte-identity claim covers the multi-tenant path with zero shedding
    tenant_of = ["a" if i % 2 == 0 else "b" for i in range(len(chunks))]

    def make_ops():
        return [wf.Map(lambda t: {"v": t.v * 2.0 + 1.0})]

    def collect_out(acc):
        def cb(view):
            if view is not None:
                acc.extend(zip(view["id"].tolist(),
                               np.asarray(view["payload"]["v"]).tolist()))
        return cb

    # oracle: the same chunks through a plain RecordSource pipeline
    oracle = []
    wf.Pipeline(wf.RecordSource(lambda: iter(chunks), dt, key_field="key",
                                ts_field="ts", num_keys=8),
                make_ops(), wf.Sink(collect_out(oracle)),
                batch_size=chunk).run()

    mon_dir = tempfile.mkdtemp(prefix="wf_chaos_serve_")
    got = []
    src = SocketSource("tcp://127.0.0.1:0", dt, key_field="key",
                       ts_field="ts", num_keys=8, replay=len(chunks) + 8)
    rt = ServingRuntime(
        src, make_ops(), wf.Sink(collect_out(got)), batch_size=chunk,
        serving={"tenants": [{"id": "a"}, {"id": "b"}]},
        monitoring=mon_dir)
    rt.register_graph("twin", make_ops())
    src.start()                      # bind now: the client needs the port
    thread = rt.run_background()

    def decoded_stable():
        # wait for the ingest side to drain a killed connection's kernel
        # buffer before the overlap re-send, so chunk admission order
        # stays the wire send order (the id-identity precondition)
        last = -1
        for _ in range(100):
            cur = src.frames_decoded + src.frames_torn + src.frames_dup
            if cur == last:
                return
            last = cur
            time.sleep(0.05)

    client = RecordClient(src.endpoint)
    kill_at = int(rng.randint(len(chunks) // 4, 3 * len(chunks) // 4))
    swap_at = kill_at // 2           # always before the kill: the swap
    #                                  frame must survive the peer death
    sent = {}                        # tenant -> [(seq, chunk_bytes)]
    for i, c in enumerate(chunks[:kill_at]):
        t = tenant_of[i]
        seq = client.send(c.tobytes(), tenant=t)
        sent.setdefault(t, []).append((seq, c.tobytes()))
        if i == swap_at:
            client.send_swap("twin")
    client.send_garbage(b"TORN BYTES IN FLIGHT " * 3)
    client.kill()                    # abrupt peer death, no EOS
    decoded_stable()
    client.reconnect()
    # the client has no ack channel, so re-send EVERYTHING already sent
    # (original seqs): the server drops the overlap as dup and admits only
    # what the kill actually lost — replay, never loss or duplication
    for t, frames in sent.items():
        for seq, blob in frames:
            client.send(blob, tenant=t, seq=seq)
    for i in range(kill_at, len(chunks)):
        t = tenant_of[i]
        client.send(chunks[i].tobytes(), tenant=t)
    client.send_eos("a")             # default eos policy: first eos ends it
    client.close()
    thread.join(timeout=60.0)

    problems = []
    if thread.is_alive():
        problems.append("serving drive thread did not reach EOS")
    if rt.background_error is not None:
        problems.append(f"serving run raised "
                        f"{type(rt.background_error).__name__}: "
                        f"{rt.background_error}")
    if sorted(got) != sorted(oracle):
        missing = set(map(tuple, oracle)) - set(map(tuple, got))
        extra = set(map(tuple, got)) - set(map(tuple, oracle))
        problems.append(f"DIVERGED from the RecordSource oracle: "
                        f"missing={len(missing)} extra={len(extra)}")
    if src.frames_torn < 1:
        problems.append("no torn frame — the garbage/kill injection never "
                        "exercised resync")
    if src.frames_dup < 1:
        problems.append("no duplicate frame — the reconnect overlap never "
                        "exercised dedup")
    if rt.swaps_applied != 1:
        problems.append(f"swaps_applied={rt.swaps_applied}, want 1 (the "
                        f"wire-driven hot swap)")
    if rt.graph_label != "twin":
        problems.append(f"live graph is {rt.graph_label!r}, want 'twin'")
    try:
        with open(os.path.join(mon_dir, "snapshot.json")) as f:
            snap = json.load(f)
        srv = snap.get("serving") or {}
        if srv.get("graph") != "twin":
            problems.append("snapshot serving.graph did not record the swap")
        tenants = srv.get("tenants") or {}
        for t in ("a", "b"):
            if t not in tenants:
                problems.append(f"snapshot serving.tenants missing {t!r}")
            elif tenants[t].get("shed", 0):
                problems.append(f"tenant {t!r} shed "
                                f"{tenants[t]['shed']} batch(es) — "
                                f"unlimited tenants must never shed")
    except (OSError, ValueError) as e:
        problems.append(f"cannot read the serving snapshot: {e}")
    counters = {"torn": src.frames_torn, "dup": src.frames_dup,
                "decoded": src.frames_decoded, "kill_at": kill_at}
    src.close()
    shutil.rmtree(mon_dir, ignore_errors=True)
    return problems, counters


def plan_for(seed, threaded=False, shards=0):
    if threaded:
        # the threaded driver has no replay machinery: stalls only (delay,
        # never drop) — the watchdog must notice, results must not change
        return FaultPlan([FaultSpec("queue.stall", kind="stall", p=0.15,
                                    stall_s=0.4)], seed=seed)
    specs = [FaultSpec("source.next", p=0.06),
             FaultSpec("chain.step", p=0.08),
             FaultSpec("sink.consume", p=0.10)]
    if shards:
        # shard-local drills: random shard step kills (each recovers by
        # replaying ONLY that shard's key range) + a torn handoff against
        # the mid-stream live reshard (the seal must be discarded and the
        # move re-derived at the same barrier)
        specs += [FaultSpec("shard.kill", p=0.05),
                  FaultSpec("reshard.handoff", kind="torn", p=0.25,
                            max_fires=1)]
    return FaultPlan(specs, seed=seed)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--total", type=int, default=400)
    ap.add_argument("--batch", type=int, default=40)
    ap.add_argument("--controller", action="store_true",
                    help="run every driver with the adaptive control plane "
                    "active (admission/backpressure; baselines use the same "
                    "controller, so shedding must stay deterministic)")
    ap.add_argument("--dispatch", type=int, default=0, metavar="K",
                    help="run every CHAOS run with scan dispatch (K-fused "
                    "push_many) while the baselines stay per-batch — the "
                    "fused path must match the per-batch fault-free oracle "
                    "byte-for-byte")
    ap.add_argument("--shards", type=int, default=0, metavar="N",
                    help="run the supervised drivers (pipeline + graph) "
                    "through N-way shard-local supervision (plus a live "
                    "N->2N reshard on the pipeline driver) with shard-kill "
                    "and torn-handoff injection added to each seed's plan; "
                    "baselines stay unsharded, so every seed asserts "
                    "shard-count invariance and shard-local recovery at "
                    "once")
    ap.add_argument("--remediate", action="store_true",
                    help="supervised pipeline runs (baselines AND chaos) "
                    "carry barrier remediation + deterministic admission "
                    "(byte-identity must still hold), plus one live "
                    "threaded closed-loop leg under queue.stall asserting "
                    "OK -> PAGE -> actuate -> recovery to OK with the "
                    "incident bundle recording the actions")
    ap.add_argument("--serve", action="store_true",
                    help="run ONLY the serving closed-loop legs (one per "
                    "seed): two tenants over a real loopback socket, a "
                    "seeded peer kill mid-stream + garbage + reconnect "
                    "overlap + a live graph hot-swap — outputs must be "
                    "byte-identical to a RecordSource oracle (zero loss, "
                    "torn frames resync'd, overlap deduped)")
    args = ap.parse_args()
    if args.serve:
        failures = 0
        for seed in range(args.seeds):
            t0 = time.time()
            problems, ctr = run_serve_loop(seed)
            ok = not problems
            print(f"[seed {seed}] serve: kill@chunk {ctr['kill_at']}, "
                  f"{ctr['decoded']} decoded / {ctr['torn']} torn / "
                  f"{ctr['dup']} dup, {'OK' if ok else 'FAILED'} "
                  f"({time.time() - t0:.1f}s)")
            for p in problems:
                print(f"            {p}")
            failures += bool(problems)
        if failures:
            print(f"FAIL: {failures} divergent serving run(s)")
            return 1
        print("PASS: all serving chaos runs byte-identical to the "
              "RecordSource oracle")
        return 0
    if args.shards and args.dispatch:
        ap.error("--shards excludes --dispatch on the supervised drivers "
                 "(WF115: a fused group failure has no single shard's "
                 "replay extent)")

    #: drivers that route through the sharded supervisors under --shards
    sharded_drivers = {"pipeline", "graph", "graph_det"}
    drivers = {"pipeline": run_pipeline, "graph": run_graph,
               "graph_det": run_graph_det, "threaded": run_threaded}
    baselines = {}
    for name, fn in drivers.items():
        t0 = time.time()
        kw = ({"remediate": True}
              if (args.remediate and name == "pipeline") else {})
        baselines[name] = fn(args.total, args.batch,
                             controller=args.controller, **kw)
        print(f"[baseline] {name}: {len(baselines[name])} results "
              f"({time.time() - t0:.1f}s)")

    divergences = 0
    for seed in range(args.seeds):
        for name, fn in drivers.items():
            n_shards = args.shards if name in sharded_drivers else 0
            inj = FaultInjector(plan_for(seed, threaded=(name == "threaded"),
                                         shards=n_shards))
            t0 = time.time()
            try:
                kw = {"shards": n_shards} if n_shards else {}
                if args.remediate and name == "pipeline":
                    kw["remediate"] = True
                out = fn(args.total, args.batch, faults=inj,
                         controller=args.controller,
                         dispatch=args.dispatch,   # 0 = off (every driver)
                         **kw)
            except Exception as e:          # noqa: BLE001
                print(f"[seed {seed}] {name}: RUN FAILED {type(e).__name__}: "
                      f"{e} ({len(inj.fired)} faults injected)")
                divergences += 1
                continue
            ok = out == baselines[name]
            print(f"[seed {seed}] {name}: {len(inj.fired)} faults injected, "
                  f"{'OK' if ok else 'DIVERGED'} ({time.time() - t0:.1f}s)")
            if not ok:
                divergences += 1
                missing = set(baselines[name]) - set(out)
                extra = set(out) - set(baselines[name])
                print(f"            missing={len(missing)} extra={len(extra)}")
    if args.remediate:
        t0 = time.time()
        problems, n_applies, n_faults = run_closed_loop(seed=0)
        ok = not problems
        print(f"[closed-loop] threaded: {n_faults} faults injected, "
              f"{n_applies} remediation action(s), "
              f"{'OK' if ok else 'FAILED'} ({time.time() - t0:.1f}s)")
        if not ok:
            for p in problems:
                print(f"            {p}")
            divergences += 1
    ctr = faults_mod.counters()
    print(f"\ncounters: {ctr}")
    if divergences:
        print(f"FAIL: {divergences} divergent run(s)")
        return 1
    print("PASS: all chaos runs byte-identical to the fault-free baselines")
    return 0


if __name__ == "__main__":
    sys.exit(main())
