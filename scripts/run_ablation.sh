#!/bin/bash
# Per-prefix YSB ablation, one fresh process per prefix (r03 integrity rule).
# Results append to scripts/ablation.log. Usage: run_ablation.sh [batch]
# Exits 3 (via ok_or_bail) if the tunnel dies mid-run — callers must check.
cd /root/repo
LOG=scripts/ablation.log
. scripts/tunnel_lib.sh
echo "=== $(date -u +%FT%TZ) batch=${1:-1048576}" >> "$LOG"

for n in 0 1 2 3 4; do
  # HLO dumps for the join/rekey/window prefixes: the fusion diff between
  # hlo_ablate_3 and hlo_ablate_4 is the in-chain-slowdown evidence
  dump=""; [ "$n" -ge 2 ] && dump="WF_DUMP_HLO=1"
  env $dump timeout 900 python scripts/probe_ysb_ablation.py "$n" "${1:-1048576}" >> "$LOG" 2>&1
  ok_or_bail $? "$LOG"
done

# Mosaic lowering precheck on tiny shapes, one fresh short-timeout process per
# kernel: a variant whose store pattern Mosaic refuses (the "ds" dynamic
# minor-dim slice is the suspect) must fail HERE in seconds, not burn a
# 900 s probe slot mid-window. A precheck failure is only recorded as a
# lowering verdict when the tunnel is still alive (ok_or_bail distinguishes);
# probes below only run for variants that pass.
hist_ok=""
for pv in ds mm; do
  if timeout 300 python -c "
import numpy as np, jax.numpy as jnp
from windflow_tpu.ops.histogram import keyed_pane_histogram_pallas, _scatter_hist
key = jnp.asarray(np.arange(2048) % 8, jnp.int32)
pane = jnp.asarray(np.arange(2048) // 600 + 30, jnp.int32)
valid = jnp.ones((2048,), bool)
got = keyed_pane_histogram_pallas(key, pane, valid, 8, 32, placement='$pv')
assert (np.asarray(got) == np.asarray(_scatter_hist(key, pane, valid, 8, 32))).all()
print('hist $pv lowers + matches')
" >> "$LOG" 2>&1; then hist_ok="$hist_ok $pv"; else
    ok_or_bail 1 "$LOG"
    echo "PRECHECK hist $pv FAILED with the tunnel alive (Mosaic verdict; skipping its probes)" >> "$LOG"; fi
done
lookup_ok=0
if timeout 300 python -c "
import numpy as np, jax.numpy as jnp
from windflow_tpu.ops.lookup import _pallas_factored_lookup
t = jnp.asarray(np.arange(1000, dtype=np.int32) // 10)
i = jnp.asarray((np.arange(8192) * 7919 % 1000).astype(np.int32))
got = _pallas_factored_lookup(t, i)
assert (np.asarray(got) == np.asarray(t)[np.asarray(i)]).all()
print('lookup pallas lowers + matches')
" >> "$LOG" 2>&1; then lookup_ok=1; else
  ok_or_bail 1 "$LOG"
  echo "PRECHECK lookup pallas FAILED with the tunnel alive (Mosaic verdict; skipping its probes)" >> "$LOG"; fi

# Decisive cond-flattening diagnostic: if prefix 4 collapses with the locality
# cond bypassed, the serialized scatter FALLBACK branch was executing every
# step in-chain (select-both-branches flattening), and the fix is the cond
# structure, not the fast path.
echo "--- WF_HISTOGRAM_FORCE_FAST=1 prefix 4" >> "$LOG"
WF_HISTOGRAM_FORCE_FAST=1 timeout 900 python scripts/probe_ysb_ablation.py 4 "${1:-1048576}" >> "$LOG" 2>&1
ok_or_bail $? "$LOG"

# Pallas-impl A/Bs against the XLA ABLATE rows above, one fresh process each:
# window-insert kernel alone, join kernel alone, and the all-Pallas chain.
best_hist=""
for pv in $hist_ok; do
  impl=pallas; [ "$pv" = mm ] && impl=pallas_mm
  echo "--- WF_HISTOGRAM_IMPL=$impl prefix 4" >> "$LOG"
  WF_HISTOGRAM_IMPL=$impl timeout 900 python scripts/probe_ysb_ablation.py 4 "${1:-1048576}" >> "$LOG" 2>&1
  ok_or_bail $? "$LOG"
  best_hist=$impl
done
if [ "$lookup_ok" = 1 ]; then
  echo "--- WF_LOOKUP_IMPL=pallas prefix 2" >> "$LOG"
  WF_LOOKUP_IMPL=pallas timeout 900 python scripts/probe_ysb_ablation.py 2 "${1:-1048576}" >> "$LOG" 2>&1
  ok_or_bail $? "$LOG"
  if [ -n "$best_hist" ]; then
    echo "--- both pallas prefix 4 (hist=$best_hist)" >> "$LOG"
    WF_LOOKUP_IMPL=pallas WF_HISTOGRAM_IMPL=$best_hist timeout 900 python scripts/probe_ysb_ablation.py 4 "${1:-1048576}" >> "$LOG" 2>&1
    ok_or_bail $? "$LOG"
  fi
fi
# refresh the stateless capture under process isolation: the in-session row
# measured post-YSB dispatch degradation (1.83 ms/step at 0.07% HBM), not the
# program
timeout 900 python -c "
import bench
r = bench.capture_stateless_isolated()
print('stateless isolated:', r[0] / 1e6, 'M t/s,', r[1] * 1e3, 'ms/step')
" >> "$LOG" 2>&1
ok_or_bail $? "$LOG"
tail -22 "$LOG"
