#!/bin/bash
# Per-prefix YSB ablation, one fresh process per prefix (r03 integrity rule).
# Results append to scripts/ablation.log. Usage: run_ablation.sh [batch]
cd /root/repo
LOG=scripts/ablation.log
echo "=== $(date -u +%FT%TZ) batch=${1:-1048576}" >> "$LOG"
for n in 0 1 2 3 4; do
  timeout 900 python scripts/probe_ysb_ablation.py "$n" "${1:-1048576}" >> "$LOG" 2>&1
done
tail -6 "$LOG"
