#!/usr/bin/env python3
"""wf_health — runtime-health inspection CLI (HBM / compile / device time).

Reads a monitoring run's artifacts (``snapshot.json`` + ``snapshots.jsonl``
time series + ``events.jsonl``) produced with the health sub-toggle on and
renders:

- the **HBM memory ledger**: per-device bytes in use / limit / headroom with
  ``[HEADROOM-RISK]`` trend flags (the ``wf_state.py`` OVERFLOW-RISK
  convention applied to device memory), live-buffer totals, per-operator
  state-pytree footprints, and executable footprints;
- the **compile/retrace ledger**: compile counters (fresh / shape-retrace /
  UNEXPECTED retraces of warm executables) plus the journaled compile
  sequence — cause, cache key, duration, AOT cost flops/bytes — and any
  ``retrace_unexpected`` / ``kernel_resolve`` events;
- **device-time attribution**: sampled host-dispatch vs device milliseconds
  per stage with the dispatch-bound classifier — stages whose host overhead
  is >= 50% of their device time are the fusion candidates for whole-graph
  single-dispatch (ROADMAP item 2).

**Fleet federation**: ``--merge DIR [DIR...]`` folds N per-host monitoring
directories (or ``snapshots.jsonl`` paths) into one fleet view — counters
summed, watermark frontier min'd, pressure max'd, per-host provenance kept
(``device_health.merge_snapshots``), ahead of the multi-host arc.

Produce the inputs with::

    WF_MONITORING=1 WF_MONITORING_HEALTH=1 python my_run.py
    python scripts/wf_health.py --monitoring-dir wf_monitoring

Stdlib only (``observability/device_health.py`` + ``journal.py`` are loaded
by file path — the ``wf_trace.py`` convention), so this works on any box the
artifacts were copied to, without JAX installed.

Exit codes: 0 = report rendered, 2 = missing/unreadable inputs or usage
error (``tests/test_device_health.py`` pins the contract).
"""

import argparse
import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_device_health():
    """Load observability/device_health.py (and the journal module its
    relative import names, plus slo.py for the incident-bundle readers) by
    file path under a synthetic package — no windflow_tpu package import,
    no JAX."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in ("journal", "device_health", "slo"):
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return sys.modules["wf_obs.device_health"], sys.modules["wf_obs.slo"]


def _fmt_bytes(n):
    if n is None:
        return "—"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


# ------------------------------------------------------------ report pieces


def memory_report(snap, series):
    lines = ["== HBM memory ledger =="]
    sec = snap.get("health") or {}
    devices = sec.get("devices") or []
    if not devices and not sec:
        lines.append("  (no health section — run with WF_MONITORING=1 "
                     "WF_MONITORING_HEALTH=1 / MonitoringConfig("
                     "health=True))")
        return lines
    # headroom trend over the series (first/last/min per device)
    trend = {}
    for s in series or [snap]:
        for d in (s.get("health") or {}).get("devices", []):
            if d.get("headroom_bytes") is not None:
                trend.setdefault(d.get("device", "?"), []).append(
                    d["headroom_bytes"])
    risky = set(sec.get("headroom_risk") or [])
    for d in devices:
        label = d.get("device", "?")
        bits = [f"kind={d.get('kind', '?')}"]
        if d.get("bytes_in_use") is not None:
            bits.append(f"in_use={_fmt_bytes(d['bytes_in_use'])}")
        if d.get("bytes_limit") is not None:
            bits.append(f"limit={_fmt_bytes(d['bytes_limit'])}")
        if d.get("headroom_bytes") is not None:
            bits.append(f"headroom={_fmt_bytes(d['headroom_bytes'])}")
            vals = trend.get(label, [d["headroom_bytes"]])
            bits.append(f"(min over run {_fmt_bytes(min(vals))})")
        flag = "  [HEADROOM-RISK]" if label in risky else ""
        if (d.get("headroom_bytes") is None
                and d.get("bytes_in_use") is None):
            bits.append("(no memory_stats on this backend)")
        lines.append(f"  {label:<16} " + "  ".join(bits) + flag)
    if sec.get("live_buffer_count") is not None:
        lines.append(f"  live buffers: {sec['live_buffer_count']} arrays, "
                     f"{_fmt_bytes(sec.get('live_buffer_bytes'))}")
    sb = sec.get("state_bytes") or {}
    if sb:
        lines.append("  per-operator state footprints:")
        for name, n in sorted(sb.items(), key=lambda kv: -kv[1]):
            lines.append(f"    {name:<28} {_fmt_bytes(n)}")
    # tiered-state cross-reference: when headroom is at risk, name WHICH
    # table to shrink — per-operator hot occupancy beside its HBM footprint
    # (a hot table far below 100% is reclaimable headroom; one pegged at
    # 100% with spill movement is already doing its job)
    tiers = [(row.get("name", "?"), row["event_time"]["tier"])
             for row in snap.get("operators", [])
             if isinstance((row.get("event_time") or {}).get("tier"), dict)]
    if tiers and (risky or sb):
        lines.append("  tiered tables (hot occupancy vs footprint — the "
                     "HEADROOM-RISK shrink candidates):")
        for name, t in sorted(
                tiers, key=lambda kv: -(sb.get(kv[0], 0) or 0)):
            bits = []
            if t.get("hot_used") is not None:
                bits.append(f"hot={t.get('hot_used')}/{t.get('hot_slots')}"
                            + (f" ({t['hot_pct']}%)"
                               if t.get("hot_pct") is not None else ""))
            if t.get("cold_keys") is not None:
                bits.append(f"cold={t['cold_keys']} keys")
            for k in ("state_spills", "state_readmits"):
                if t.get(k):
                    bits.append(f"{k.split('_')[1]}={t[k]}")
            if name in sb:
                bits.append(f"hbm={_fmt_bytes(sb[name])}")
            lines.append(f"    {name:<28} " + "  ".join(bits))
    exes = sec.get("executables") or {}
    if exes:
        lines.append("  executable footprints (cache key: arg/out/temp/"
                     "code bytes):")
        for key, row in sorted(exes.items()):
            lines.append(
                f"    {key} {row.get('label', '?')}/{row.get('kind', '?')}"
                f"  arg={_fmt_bytes(row.get('argument_bytes'))}"
                f"  out={_fmt_bytes(row.get('output_bytes'))}"
                f"  temp={_fmt_bytes(row.get('temp_bytes'))}"
                f"  code={_fmt_bytes(row.get('code_bytes'))}")
    return lines


def compile_report(snap, journal):
    lines = ["== compile/retrace ledger =="]
    comp = (snap.get("health") or {}).get("compile") or {}
    if comp:
        lines.append(
            f"  {comp.get('compiles', 0)} compiles: "
            f"{comp.get('retraces', 0)} shape retraces "
            f"(capacity/K switches), "
            f"{comp.get('retraces_unexpected', 0)} UNEXPECTED retraces "
            f"(warm executables silently recompiled), "
            f"{comp.get('compile_s_total', 0)} s total, "
            f"{comp.get('kernel_resolves', 0)} kernel resolutions")
    compiles = [e for e in journal if e.get("event") == "compile"]
    if compiles:
        lines.append("  compile journal (cause / stage / key / cost):")
        for e in compiles:
            cost = ""
            if e.get("flops") is not None:
                cost = (f"  {e['flops'] / 1e6:.2f} Mflop"
                        f"/{(e.get('bytes_accessed') or 0) / 1e6:.2f} MB")
            shape = f" cap={e['capacity']}" if e.get("capacity") else ""
            shape += f" k={e['k']}" if e.get("k") else ""
            kind = ("RETRACE" if e.get("retrace")
                    else ("UNEXPECTED" if e.get("unexpected") else "compile"))
            lines.append(
                f"    {e.get('label', '?'):<10} {e.get('kind', '?'):<5} "
                f"{kind:<10} cause={e.get('cause', '?'):<17} "
                f"key={e.get('cache_key', '?')}{shape} "
                f"{e.get('compile_s', 0):.3f}s{cost}")
    unexpected = [e for e in journal
                  if e.get("event") == "retrace_unexpected"]
    if unexpected:
        lines.append("  UNEXPECTED retraces (warm executables re-traced "
                     "under an identical signature):")
        for e in unexpected:
            lines.append(f"    {e.get('label', '?')}/{e.get('kind', '?')} "
                         f"key={e.get('cache_key', '?')} "
                         f"cause={e.get('cause', '?')}")
    resolves = [e for e in journal if e.get("event") == "kernel_resolve"]
    if resolves:
        lines.append(f"  kernel resolutions: " + "  ".join(
            f"{e.get('kernel')}->{e.get('impl')}" for e in resolves[:8])
            + (" …" if len(resolves) > 8 else ""))
    if len(lines) == 1:
        lines.append("  (no compile records — health off, or nothing "
                     "compiled while the ledger was active)")
    return lines


def device_time_report(snap):
    lines = ["== device-time attribution (dispatch-bound classifier) =="]
    sec = snap.get("health") or {}
    dt = sec.get("device_time") or {}
    if not dt:
        lines.append("  (no sampled device-time points — health off or the "
                     "run was too short to hit a sampled push)")
        return lines
    bound = sec.get("dispatch_bound") or {}
    for stage, row in sorted(dt.items(),
                             key=lambda kv: -(kv[1].get("dispatch_ratio")
                                              or 0.0)):
        ratio = row.get("dispatch_ratio")
        flag = ("  [DISPATCH-BOUND -> fusion candidate]"
                if stage in bound else "")
        lines.append(
            f"  {stage:<24} device={row.get('device_ms', 0):10.3f} ms  "
            f"host-dispatch={row.get('dispatch_ms', 0):10.3f} ms  "
            f"samples={row.get('samples', 0):<5} "
            f"ratio={ratio if ratio is not None else '—'}{flag}")
    if bound:
        lines.append(f"  {len(bound)} dispatch-bound stage(s): the host "
                     f"loop, not the device, is their ceiling — the "
                     f"whole-graph fusion candidates (ROADMAP item 2)")
    return lines


def shard_report(snap, journal):
    """Per-shard supervision health: one row per shard (host-tagged in a
    fleet merge — the keys name WHICH shard is hot), occupancy + restart
    counts + last recovery duration + reshard movements, plus the journal's
    shard_restore/reshard timeline tail."""
    lines = ["shard supervision"]
    shards = snap.get("shards") or {}
    if not shards:
        lines.append("  (no shards section — run the supervised driver "
                     "with shards=N / WF_SHARDS=N and monitoring on)")
        return lines
    hot = max(shards, key=lambda k: shards[k].get("occupancy_tuples", 0))
    lines.append(f"  {len(shards)} shard(s); hottest: {hot} "
                 f"({shards[hot].get('occupancy_tuples', 0)} tuples)")
    lines.append(f"  {'shard':>12} {'tuples':>10} {'restarts':>8} "
                 f"{'recov_ms':>9} {'dead':>5} {'moves':>6} {'pos':>6}")
    for k in sorted(shards, key=lambda x: (len(x), x)):
        r = shards[k]
        flag = "  [HOT]" if k == hot and len(shards) > 1 else ""
        lines.append(
            f"  {k:>12} {r.get('occupancy_tuples', 0):>10} "
            f"{r.get('restarts', 0):>8} "
            f"{r.get('last_recovery_s', 0.0) * 1e3:>9.2f} "
            f"{r.get('dead_letters', 0):>5} {r.get('reshard_moves', 0):>6} "
            f"{r.get('committed_pos', 0):>6}{flag}")
    # reshard spans emit begin+end records — keep one line per reshard
    # (the wf_state.py shard_section convention)
    ev = [e for e in journal
          if e.get("event") in ("shard_restore", "reshard")
          and e.get("phase") != "end"]
    if ev:
        lines.append(f"  recovery/reshard events: {len(ev)} "
                     f"(last {min(5, len(ev))}):")
        for e in ev[-5:]:
            if e.get("event") == "shard_restore":
                lines.append(f"    shard_restore shard={e.get('shard')} "
                             f"at={e.get('at_batch')} "
                             f"replay_from={e.get('replay_from')} "
                             f"error={e.get('error')}")
            else:
                lines.append(f"    reshard {e.get('from_shards')}->"
                             f"{e.get('to_shards')} at={e.get('at_pos')} "
                             f"moves={e.get('moves')}"
                             + (" DISCARDED" if e.get("discarded") else ""))
    return lines


def incidents_report(slo_mod, mon_dir):
    """Cross-reference to the SLO engine's forensic bundles (count, last
    incident path + triggering SLO, torn captures) — read from the bundle
    manifests under ``<mon_dir>/incidents`` (``slo.incidents_summary``)."""
    lines = ["== incidents (SLO forensic bundles) =="]
    summ = slo_mod.incidents_summary(mon_dir)
    if not summ["count"] and not summ["torn"]:
        lines.append("  (none captured — enable with WF_SLO=1 / "
                     "MonitoringConfig(slo=...); analyze with "
                     "scripts/wf_slo.py)")
        return lines
    lines.append(f"  {summ['count']} committed bundle(s)"
                 + (f", {summ['torn']} TORN (crash mid-capture)"
                    if summ["torn"] else ""))
    last = summ.get("last")
    if last:
        lines.append(f"  last: {last['path']}")
        lines.append(f"        triggered by SLO {last.get('slo')!r} "
                     f"(state {last.get('state')})")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_health",
        description="windflow_tpu runtime-health CLI (HBM ledger, "
                    "compile/retrace ledger, device-time attribution, "
                    "fleet merge)")
    ap.add_argument("--monitoring-dir", default="wf_monitoring",
                    help="monitoring output directory (snapshot.json + "
                         "snapshots.jsonl + events.jsonl)")
    ap.add_argument("--merge", nargs="+", default=None, metavar="DIR",
                    help="merge N per-host monitoring directories (or "
                         "snapshots.jsonl paths) into one fleet view "
                         "instead of reading --monitoring-dir")
    ap.add_argument("--report", choices=("all", "memory", "compile",
                                         "device-time", "shards",
                                         "incidents"),
                    default="all",
                    help="which section(s) to render (default all)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: the (merged) snapshot's "
                         "health section + provenance")
    args = ap.parse_args(argv)

    try:
        dh, slo_mod = _load_device_health()
    except (OSError, ImportError, SyntaxError) as e:
        print(f"wf_health: cannot load observability/device_health.py from "
              f"{REPO!r}: {type(e).__name__}: {e}\n"
              f"(keep scripts/wf_health.py next to its windflow_tpu tree — "
              f"it reuses the ledger/merge helpers by file path)",
              file=sys.stderr)
        return 2
    try:
        if args.merge:
            snap, series, journal = dh.merge_monitoring_dirs(args.merge)
        else:
            snap, series = dh.load_snapshots(args.monitoring_dir)
            journal = dh.load_journal(args.monitoring_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        where = args.merge or args.monitoring_dir
        print(f"wf_health: cannot load snapshots from {where!r}: "
              f"{type(e).__name__}: {e}\n"
              f"(run with WF_MONITORING=1 WF_MONITORING_HEALTH=1, or "
              f"monitoring=MonitoringConfig(health=True))",
              file=sys.stderr)
        return 2

    if args.json:
        out = {"graph": snap.get("graph"),
               "health": snap.get("health") or {},
               "shards": snap.get("shards") or {},
               "snapshots": len(series),
               "journal_events": len(journal)}
        if not args.merge:
            out["incidents"] = slo_mod.incidents_summary(args.monitoring_dir)
        if snap.get("hosts"):
            out["hosts"] = snap["hosts"]
            out["merged_from"] = snap.get("merged_from")
        if snap.get("schema_mismatch"):
            out["schema_mismatch"] = snap["schema_mismatch"]
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    head = (f"wf_health: merged {snap.get('merged_from')} host(s): "
            + ", ".join(h.get("host", "?") for h in snap.get("hosts", []))
            if args.merge else
            f"wf_health: {args.monitoring_dir!r}")
    print(f"{head} — graph {snap.get('graph', '?')!r}, {len(series)} "
          f"snapshot(s), {len(journal)} journal event(s)")
    if snap.get("schema_mismatch"):
        # merge_snapshots flags mixed snapshot generations, never folds
        # them silently — keep the flag visible at the top of the report
        print(f"wf_health: MIXED-SCHEMA fleet — per-host snapshot schema "
              f"versions differ: "
              f"{json.dumps(snap['schema_mismatch'], sort_keys=True)}")
    blocks = []
    if args.report in ("all", "memory"):
        blocks.append(memory_report(snap, series))
    if args.report in ("all", "compile"):
        blocks.append(compile_report(snap, journal))
    if args.report in ("all", "device-time"):
        blocks.append(device_time_report(snap))
    if args.report == "shards" or (args.report == "all"
                                   and snap.get("shards")):
        blocks.append(shard_report(snap, journal))
    if args.report in ("all", "incidents"):
        if args.merge:
            # per-host forensics: a merged fleet view has no single
            # incidents/ directory — say so when incidents were asked for
            # explicitly instead of rendering nothing (indistinguishable
            # from "no incidents on the fleet")
            if args.report == "incidents":
                blocks.append(
                    ["== incidents (SLO forensic bundles) ==",
                     "  (not available in the --merge fleet view — "
                     "bundles live under each host's own "
                     "<monitoring_dir>/incidents/; run wf_health "
                     "against each host's dir)"])
        else:
            blocks.append(incidents_report(slo_mod, args.monitoring_dir))
    for b in blocks:
        print()
        print("\n".join(b))
    return 0


if __name__ == "__main__":
    sys.exit(main())
