#!/usr/bin/env python3
"""bench_trend — the bench trajectory across rounds, as a markdown table.

Each builder round leaves ``BENCH_r<NN>.json`` (single-chip ``bench.py`` run:
``rc``, ``tail``, and — when the run parsed — a ``parsed`` metric record) and
``MULTICHIP_r<NN>.json`` (8-device smoke: ``rc``/``ok``) in the repo root.
The trajectory across those rounds is otherwise invisible; this tool folds
them into one trend table with regression flags:

- **ok**       — parsed metric present, within threshold of the best round
                 so far (the regression reference is *best-so-far*, not the
                 previous round, so a slow drift cannot ratchet the bar down)
- **BEST**     — a new best value
- **REGRESSED**— value below ``(1 - threshold) * best_so_far``
- **STALE**    — the round emitted a last-good capture marked ``stale``
                 (device unreachable at capture time): reported, but it
                 neither sets nor regresses against the best
- **FAILED**   — ``rc != 0`` or no parsed metric: the round produced *no*
                 measurement.  Reported loudly (with the rc and the tail's
                 last line), never skipped — an invisible failed round reads
                 as "no regression" when the truth is "no data".

Stdlib only.  Usage::

    python scripts/bench_trend.py                   # repo root, markdown
    python scripts/bench_trend.py --threshold 0.10 --out TREND.md

Exit codes: 0 = no regressions among measured rounds, 1 = at least one
REGRESSED round, 2 = no round files found / unreadable input.
"""

import argparse
import datetime
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _round_no(path: str):
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _last_line(tail: str) -> str:
    lines = [ln.strip() for ln in (tail or "").splitlines() if ln.strip()]
    return lines[-1] if lines else ""


def _capture_age_days(captured_at):
    """Age in days of a ``captured_at`` ISO-8601 stamp (the bench capture
    wall time), or None when absent/unparseable — a STALE round re-emits a
    LAST-GOOD capture, so the same number can ride along for many rounds;
    the age says how old the measurement actually is."""
    if not captured_at:
        return None
    try:
        ts = datetime.datetime.fromisoformat(
            str(captured_at).replace("Z", "+00:00"))
    except ValueError:
        return None
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return max(0.0, (now - ts).total_seconds() / 86400.0)


def load_rounds(root: str, prefix: str):
    """Sorted (round, data) pairs for ``<prefix>_r*.json`` under ``root``."""
    out = []
    for path in glob.glob(os.path.join(root, f"{prefix}_r*.json")):
        n = _round_no(path)
        if n is None:
            continue
        with open(path) as f:
            out.append((n, json.load(f)))
    return sorted(out, key=lambda x: x[0])


def bench_rows(rounds, threshold: float):
    """One row dict per bench round: the ``parsed`` metric vs best-so-far."""
    rows, best = [], None
    for n, d in rounds:
        parsed = d.get("parsed")
        rc = d.get("rc")
        row = {"round": n, "rc": rc, "value": None, "unit": "",
               "vs_baseline": None, "stale": False, "status": "",
               "capture_age_days": None,
               "note": "", "flops_per_step": None, "bytes_per_step": None,
               "launches_per_step": None, "compiles_per_step": None,
               "shard_recovery_ms": None, "slo_pages": None}
        if parsed is None or rc not in (0, None):
            # rc=1/parsed=null rounds MUST surface — a silent skip would
            # render the failed round as "nothing happened"
            row["status"] = "FAILED"
            row["note"] = (f"rc={rc}, no parsed metric"
                           + (f" — {_last_line(d.get('tail', ''))[:80]}"
                              if d.get("tail") else ""))
            rows.append(row)
            continue
        value = parsed.get("value")
        cost = parsed.get("cost") or {}
        dispatch = parsed.get("dispatch") or {}
        health = parsed.get("health") or {}
        shard = parsed.get("shard") or {}
        slo = parsed.get("slo") or {}
        row.update(value=value, unit=parsed.get("unit", ""),
                   vs_baseline=parsed.get("vs_baseline"),
                   stale=bool(parsed.get("stale")),
                   # XLA logical cost per step (bench.py headline `cost`,
                   # the hermetic perf gate's pinned metrics): moves every
                   # round — including tunnel-down rounds via
                   # scripts/wf_perfgate.py — where the tps number cannot
                   flops_per_step=cost.get("flops_per_step"),
                   bytes_per_step=cost.get("bytes_per_step"),
                   # scan dispatch (bench.py headline `dispatch`): host
                   # executable launches per batch through the real driver —
                   # 1.0 per-batch, ~1/K fused (bench_dispatch)
                   launches_per_step=dispatch.get("launches_per_step"),
                   # compile ledger (bench.py headline `health`, PR 11's
                   # hermetic device_health ledger): jit traces per driven
                   # step through CompiledChain.push — trace stability
                   # moves every round, tunnel up or down
                   compiles_per_step=health.get("compiles_per_step"),
                   # shard-local recovery (bench.py headline `shard`): the
                   # killed shard's measured restore+replay duration — the
                   # per-shard-recovery-time trend, moving in tunnel-down
                   # rounds like the other hermetic columns (only honest
                   # drills count: a kill that diverged renders "—")
                   shard_recovery_ms=(shard.get("recovery_ms")
                                      if shard.get("kill_exact") else None),
                   # SLO engine (bench.py headline `slo`): PAGE transitions
                   # of the default spec set over a short monitored run —
                   # zero on a healthy box; a nonzero count names a
                   # latency/drop regression no throughput row attributes
                   slo_pages=slo.get("pages"))
        if value is None:
            row["status"] = "FAILED"
            row["note"] = "parsed record without a value"
        elif row["stale"]:
            # a re-emitted last-good capture is not a fresh measurement:
            # report it, keep it out of the best-so-far comparison — and
            # date it: consecutive STALE rounds repeat the SAME number, so
            # without the capture age the table reads like a fresh plateau
            row["status"] = "STALE"
            row["capture_age_days"] = _capture_age_days(
                parsed.get("captured_at"))
            note = parsed.get("staleness_reason", "stale capture")
            if parsed.get("captured_at"):
                age = row["capture_age_days"]
                note += (f"; re-emits capture from "
                         f"{parsed['captured_at']}"
                         + (f" ({age:.0f}d old)" if age is not None
                            else ""))
            row["note"] = note
        elif best is None or value > best:
            row["status"] = "BEST"
            best = value
        elif value < (1.0 - threshold) * best:
            row["status"] = "REGRESSED"
            row["note"] = (f"{(1.0 - value / best) * 100.0:.1f}% below "
                           f"best-so-far {best:g}")
        else:
            row["status"] = "ok"
        rows.append(row)
    return rows


def nexmark_rows(rounds):
    """Per-round Nexmark query throughput (the bench.py headline ``nexmark``
    record: ``{query: tps}``) plus the e2e event-time p99 record
    (``nexmark_event_time``: ``{query: lateness p99 ticks}``, rounds with
    event-time observability). Rounds predating the suite render as '—';
    failed rounds surface the same way the main table does."""
    queries, rows = [], []
    for n, d in rounds:
        nx = (d.get("parsed") or {}).get("nexmark")
        if isinstance(nx, dict):
            for q in nx:
                if q not in queries:
                    queries.append(q)
    for n, d in rounds:
        parsed = d.get("parsed")
        nx = (parsed or {}).get("nexmark")
        et = (parsed or {}).get("nexmark_event_time")
        tr = (parsed or {}).get("nexmark_tiered")
        row = {"round": n, "tps": nx if isinstance(nx, dict) else None,
               "event_time": et if isinstance(et, dict) else None,
               "tiered": tr if isinstance(tr, dict) else None,
               "status": "ok" if isinstance(nx, dict) else
               ("FAILED" if parsed is None or d.get("rc") not in (0, None)
                else "—")}
        rows.append(row)
    return sorted(queries), rows


def multichip_rows(rounds):
    rows = []
    for n, d in rounds:
        rc, ok = d.get("rc"), d.get("ok")
        row = {"round": n, "rc": rc, "devices": d.get("n_devices"),
               "status": "ok" if ok else "FAILED", "note": ""}
        if d.get("skipped"):
            row["status"], row["note"] = "SKIPPED", "no multi-device run"
        elif not ok:
            row["note"] = (f"rc={rc}"
                           + (" (timeout)" if rc == 124 else "")
                           + (f" — {_last_line(d.get('tail', ''))[:80]}"
                              if d.get("tail") else ""))
        rows.append(row)
    return rows


def _cell(s) -> str:
    """A tail excerpt with '|' in it must not break the table."""
    return str(s).replace("|", "\\|")


def _fmt(v):
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:g}"
    if isinstance(v, int) and abs(v) >= 1_000_000:
        return f"{v / 1e6:.2f}M"
    return str(v)


def render_nexmark(queries, rows) -> list:
    """The Nexmark query table beside YSB — one column per query, M t/s."""
    lines = ["", "## Nexmark queries (`parsed.nexmark`, M tuples/s)", ""]
    if not queries:
        lines += ["(no round carries a nexmark record yet — the suite "
                  "lands in the next capture)"]
        return lines
    lines.append("| round | status | " + " | ".join(queries) + " |")
    lines.append("|---|---|" + "---|" * len(queries))
    for r in rows:
        cells = []
        for q in queries:
            v = (r["tps"] or {}).get(q)
            cells.append(f"{v / 1e6:.2f}" if isinstance(v, (int, float))
                         else "—")
        lines.append(f"| r{r['round']:02d} | {r['status']} | "
                     + " | ".join(cells) + " |")
    if any(r["event_time"] for r in rows):
        # e2e event-time p99 per query (ticks): the observed-lateness
        # quantile of each query's stateful operators — the delay-tuning
        # signal next to the throughput it buys
        lines += ["", "### event-time p99 per query "
                      "(`parsed.nexmark_event_time`, ticks)", ""]
        lines.append("| round | " + " | ".join(queries) + " |")
        lines.append("|---|" + "---|" * len(queries))
        for r in rows:
            if not r["event_time"]:
                continue
            cells = [(_fmt(r["event_time"].get(q))
                      if r["event_time"].get(q) is not None else "—")
                     for q in queries]
            lines.append(f"| r{r['round']:02d} | " + " | ".join(cells) + " |")
    if any(r["tiered"] for r in rows):
        # tiered-state spill rate of the 100x-keys acceptance row
        # (`parsed.nexmark_tiered`): the HBM->host movement per step, the
        # zero-overflow-drop claim, and the bounded p99 — all host+CPU
        # measurable, so this trend moves even in tunnel-down rounds
        lines += ["", "### tiered state — 100x-keys join "
                      "(`parsed.nexmark_tiered`)", ""]
        lines.append("| round | keys | hot | spills/step | readmits/step "
                     "| overflow drops | p99 ms/step |")
        lines.append("|---|---|---|---|---|---|---|")
        for r in rows:
            t = r["tiered"]
            if not t:
                continue
            lines.append(
                f"| r{r['round']:02d} | {_fmt(t.get('keys'))} | "
                f"{_fmt(t.get('hot_capacity'))} | "
                f"{_fmt(t.get('spills_per_step'))} | "
                f"{_fmt(t.get('readmits_per_step'))} | "
                f"{_fmt(t.get('overflow_drops'))} | "
                f"{_fmt(t.get('p99_step_ms'))} |")
    return lines


def render_markdown(bench, multichip, threshold: float,
                    nexmark=None) -> str:
    lines = ["# Bench trend", ""]
    lines.append(f"Regression flag: value < (1 - {threshold:g}) x "
                 f"best-so-far among fresh (non-stale) measured rounds.")
    lines.append("")
    lines.append("## Single-chip (`BENCH_r*.json`, `parsed` metric)")
    lines.append("")
    lines.append("| round | status | value | unit | vs baseline "
                 "| age (d) | Mflop/step | MB/step | launches/step "
                 "| compiles/step | pages/run | shard recov ms | note |")
    lines.append("|---|---|---|---|---|---|---|---|---|---|---|---|---|")
    for r in bench:
        mflop = (f"{r['flops_per_step'] / 1e6:.2f}"
                 if r.get("flops_per_step") else "—")
        mb = (f"{r['bytes_per_step'] / 1e6:.2f}"
              if r.get("bytes_per_step") else "—")
        lps = (f"{r['launches_per_step']:g}"
               if r.get("launches_per_step") else "—")
        cps = (f"{r['compiles_per_step']:g}"
               if r.get("compiles_per_step") else "—")
        # SLO pages/run beside compiles/step: 0 is the healthy reading, so
        # render a real 0 (None = the round predates the slo block)
        pg = (f"{r['slo_pages']:g}"
              if r.get("slo_pages") is not None else "—")
        srm = (f"{r['shard_recovery_ms']:g}"
               if r.get("shard_recovery_ms") is not None else "—")
        # capture age: meaningful on STALE rounds (how old the re-emitted
        # last-good number is); fresh rounds measured "now", render —
        age = (f"{r['capture_age_days']:.0f}"
               if r.get("capture_age_days") is not None else "—")
        lines.append(f"| r{r['round']:02d} | {r['status']} "
                     f"| {_fmt(r['value'])} | {r['unit'] or '—'} "
                     f"| {_fmt(r['vs_baseline'])} | {age} "
                     f"| {mflop} | {mb} | {lps} | {cps} | {pg} | {srm} "
                     f"| {_cell(r['note'] or '')} |")
    if not bench:
        lines.append("| — | — | — | — | — | — | — | — | — | — | — | — "
                     "| no BENCH_r*.json found |")
    if nexmark is not None:
        lines += render_nexmark(*nexmark)
    lines.append("")
    lines.append("## Multi-chip smoke (`MULTICHIP_r*.json`)")
    lines.append("")
    lines.append("| round | status | devices | note |")
    lines.append("|---|---|---|---|")
    for r in multichip:
        lines.append(f"| r{r['round']:02d} | {r['status']} "
                     f"| {r['devices'] if r['devices'] is not None else '—'} "
                     f"| {_cell(r['note'] or '')} |")
    if not multichip:
        lines.append("| — | — | — | no MULTICHIP_r*.json found |")
    lines.append("")
    n_fail = sum(1 for r in bench + multichip if r["status"] == "FAILED")
    n_reg = sum(1 for r in bench if r["status"] == "REGRESSED")
    n_stale = sum(1 for r in bench if r["status"] == "STALE")
    lines.append(f"{len(bench)} bench round(s): {n_reg} regressed, "
                 f"{n_stale} stale, "
                 f"{sum(1 for r in bench if r['status'] == 'FAILED')} failed; "
                 f"{len(multichip)} multichip round(s), "
                 f"{n_fail} failed total.")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_trend",
        description="fold BENCH_r*/MULTICHIP_r* rounds into a markdown "
                    "trend table with regression flags")
    ap.add_argument("--root", default=REPO,
                    help="directory holding the round files (default: repo)")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="regression threshold vs best-so-far (default 0.05)")
    ap.add_argument("--out", default=None,
                    help="write the markdown here instead of stdout")
    args = ap.parse_args(argv)

    try:
        bench = load_rounds(args.root, "BENCH")
        multichip = load_rounds(args.root, "MULTICHIP")
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_trend: unreadable round file: {e}", file=sys.stderr)
        return 2
    if not bench and not multichip:
        print(f"bench_trend: no BENCH_r*.json / MULTICHIP_r*.json under "
              f"{args.root!r}", file=sys.stderr)
        return 2
    brows = bench_rows(bench, args.threshold)
    mrows = multichip_rows(multichip)
    md = render_markdown(brows, mrows, args.threshold,
                         nexmark=nexmark_rows(bench))
    if args.out:
        with open(args.out, "w") as f:
            f.write(md)
        print(f"bench_trend: wrote {args.out}")
    else:
        print(md, end="")
    return 1 if any(r["status"] == "REGRESSED" for r in brows) else 0


if __name__ == "__main__":
    sys.exit(main())
