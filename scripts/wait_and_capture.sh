#!/bin/bash
# Probe the tunneled TPU every 120s; on first success run the full bench capture.
# Writes probe log to scripts/tunnel_watch.log and capture output to scripts/capture_r05_*.log
# Standalone YSB result is persisted through bench.record()/record_headline() so a
# transient tunnel window still updates bench_captures/last_good.json even if the
# full capture never completes.
cd /root/repo
LOG=scripts/tunnel_watch.log
echo "$(date -u +%FT%TZ) watcher start" >> "$LOG"
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jax.device_put(jnp.ones((1024,), jnp.float32))
assert float((x*2).sum()) == 2048.0
print('probe ok:', d)
" >> "$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) TUNNEL UP — starting capture" >> "$LOG"
    break
  fi
  echo "$(date -u +%FT%TZ) probe failed/hung" >> "$LOG"
  sleep 120
done
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
timeout 3000 python -c "
import bench
tps, step, roof = bench.bench_ysb()
bench.record('ysb', {'tps': tps, 'step_s': step, 'batch': bench.BATCH,
                     'roofline': roof},
             methodology='watcher-standalone')
bench.record_headline({'metric': 'YSB tuples/sec/chip', 'value': round(tps),
                       'unit': 'tuples/s',
                       'vs_baseline': round(tps / bench.BASELINE_TPS, 3)},
                      methodology='watcher-standalone')
print('YSB:', tps / 1e6, 'M t/s,', step * 1e3, 'ms/step')
" > "scripts/capture_r05_ysb_$STAMP.log" 2>&1
echo "$(date -u +%FT%TZ) ysb done rc=$?" >> "$LOG"
WF_BENCH_ALL=1 timeout 7200 python bench.py > "scripts/capture_r05_full_$STAMP.log" 2>&1
echo "$(date -u +%FT%TZ) full capture done rc=$?" >> "$LOG"
