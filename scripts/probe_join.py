"""Isolated probe for the YSB campaign-join stage (BASELINE.md ablation: 2.4 ms
marginal at 1M batch vs a ~0.3 ms HBM-traffic bound for the factored one-hot
lookup). Mirrors the probe recipe that cracked the histogram stage: measure each
variant standalone on precomputed inputs AND in the source->filter->join prefix,
in a fresh process per variant (run via scripts/run_join_probes.sh).

Usage: python scripts/probe_join.py <variant> [batch]
Variants:
  prefix2_base    source+filter only (the ablation baseline)
  prefix2_<v>     source+filter+join variant <v>
  standalone_<v>  join variant <v> on precomputed device inputs
where <v> in: factored (current), factored_bf16, take, barrier (factored with
optimization_barrier-pinned inputs), div (integer ad//ADS_PER_CAMPAIGN — the
fixture table is contiguous, bound of any real lookup), pallas_gather (per-lane
VMEM gather in a Pallas kernel, if Mosaic supports it), pallas_onehot (factored
lookup as ONE Pallas kernel, rows intermediate VMEM-resident).
Prints one line: PROBE <name> <ms_per_step>. Set WF_DUMP_HLO=1 to also write the
optimized HLO to scripts/hlo_<name>.txt.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("WF_CPU"):           # smoke-test escape hatch (dead tunnel)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from windflow_tpu.batch import CTRL_DTYPE
from windflow_tpu.benchmarks import ysb
from windflow_tpu.ops.lookup import _factored_lookup, table_lookup

BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 20
STEPS = 30
CAMP_OF = jnp.asarray(np.arange(ysb.N_ADS) // ysb.ADS_PER_CAMPAIGN, CTRL_DTYPE)


def _factored_bf16(table, idx):
    """Factored lookup with the one-hot and table in bf16 (campaign ids < 256
    are bf16-exact); halves the matmul-side HBM traffic."""
    K = table.shape[0]
    K2 = 1 << max(1, (K - 1).bit_length() // 2)
    K1 = (K + K2 - 1) // K2
    t2 = jnp.pad(table, (0, K1 * K2 - K)).reshape(K1, K2).astype(jnp.bfloat16)
    hi = idx // K2
    lo = idx - hi * K2
    ohhi = (hi[:, None] == jnp.arange(K1, dtype=idx.dtype)).astype(jnp.bfloat16)
    rows = jax.lax.dot_general(ohhi, t2, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.bfloat16)
    ohlo = lo[:, None] == jnp.arange(K2, dtype=idx.dtype)
    return jnp.sum(jnp.where(ohlo, rows, jnp.bfloat16(0)),
                   axis=1).astype(table.dtype)


def _barrier_factored(table, idx):
    idx = jax.lax.optimization_barrier(idx)
    return jax.lax.optimization_barrier(_factored_lookup(table, idx))


def _pallas_gather(table, idx):
    """Per-lane VMEM gather inside a Pallas kernel — works iff Mosaic supports
    vector dynamic gather on this TPU generation; the probe harness exists to
    find out."""
    import jax.experimental.pallas as pl
    C, K = idx.shape[0], table.shape[0]
    BLK = 8192
    assert C % BLK == 0, f"pallas probe needs batch % {BLK} == 0, got {C}"

    def kern(t_ref, i_ref, o_ref):
        o_ref[...] = t_ref[...][i_ref[...]]

    return pl.pallas_call(
        kern,
        grid=(C // BLK,),
        in_specs=[pl.BlockSpec((K,), lambda i: (0,)),
                  pl.BlockSpec((BLK,), lambda i: (i,))],
        out_specs=pl.BlockSpec((BLK,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((C,), table.dtype),
    )(table, idx)


def _pallas_onehot(table, idx):
    """The PRODUCTION one-kernel factored lookup
    (windflow_tpu.ops.lookup._pallas_factored_lookup): rows intermediate
    VMEM-resident. Imported, not duplicated — the probe decides whether to
    adopt that exact function in the chain, so it must measure it."""
    from windflow_tpu.ops.lookup import _pallas_block, _pallas_factored_lookup
    assert _pallas_block(idx.shape[0]), \
        f"batch {idx.shape[0]} not blockable by the production kernel"
    return _pallas_factored_lookup(table, idx)


VARIANTS = {
    "factored": lambda ad: _factored_lookup(CAMP_OF, ad),
    "factored_bf16": lambda ad: _factored_bf16(CAMP_OF, ad),
    "take": lambda ad: jnp.take(CAMP_OF, ad),
    "barrier": lambda ad: _barrier_factored(CAMP_OF, ad),
    "div": lambda ad: ad // ysb.ADS_PER_CAMPAIGN,
    "pallas_gather": lambda ad: _pallas_gather(CAMP_OF, ad),
    "pallas_onehot": lambda ad: _pallas_onehot(CAMP_OF, ad),
}


def _time(step, carry):
    carry = step(carry, 0)
    jax.block_until_ready(carry)
    times = []
    pos = 1
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            carry = step(carry, pos * BATCH)
            pos += 1
        jax.block_until_ready(carry)
        times.append(time.perf_counter() - t0)
    return sorted(times)[1] / STEPS


def _maybe_dump(name, fn, *args):
    if os.environ.get("WF_DUMP_HLO"):
        txt = jax.jit(fn).lower(*args).compile().as_text()
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"hlo_{name}.txt")
        with open(path, "w") as f:
            f.write(txt)


def prefix(variant):
    src = ysb.make_source(total=(3 * STEPS + 2) * BATCH)
    # None = prefix2_base (source+filter only); anything else must be a known
    # variant — .get would silently measure the baseline under a typo'd name
    look = None if variant is None else VARIANTS[variant]

    @jax.jit
    def step(carry, start):
        b = src.make_batch(jnp.asarray(start, jnp.int32), BATCH)
        keep = b.valid & (b.payload["event_type"] == 0)
        if look is not None:
            cmp = look(b.payload["ad_id"])
            return carry + jnp.sum(jnp.where(keep, cmp, 0))
        return carry + jnp.sum(keep.astype(jnp.int32))

    _maybe_dump(f"prefix2_{variant or 'base'}", step, jnp.int32(0), 0)
    return _time(step, jnp.int32(0))


def standalone(variant):
    look = VARIANTS[variant]
    rng = np.random.default_rng(0)
    ad = jnp.asarray(rng.integers(0, ysb.N_ADS, BATCH).astype(np.int32))

    @jax.jit
    def step(carry, _start):
        # data-depend on carry so steps chain (valid async timing)
        a = (ad + carry % 2).astype(jnp.int32) % ysb.N_ADS
        return carry + jnp.sum(look(a))

    _maybe_dump(f"standalone_{variant}", step, jnp.int32(0), 0)
    return _time(step, jnp.int32(0))


if __name__ == "__main__":
    name = sys.argv[1]
    if name == "prefix2_base":
        dt = prefix(None)
    elif name.startswith("prefix2_"):
        dt = prefix(name[len("prefix2_"):])
    elif name.startswith("standalone_"):
        dt = standalone(name[len("standalone_"):])
    else:
        raise SystemExit(f"unknown probe {name}")
    print(f"PROBE {name} {dt * 1e3:.4f} ms/step (batch={BATCH})")
