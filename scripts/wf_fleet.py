#!/usr/bin/env python3
"""wf_fleet — fleet telemetry aggregator CLI.

The daemon side of the fleet telemetry plane (``observability/fleet.py``):
each monitored host's Reporter tick streams length-framed snapshot deltas
over TCP/Unix socket (``MonitoringConfig.telemetry`` / ``WF_TELEMETRY``),
and this process folds them into ONE rolling fleet view — written in the
exact Reporter schema (``snapshot.json`` + ``snapshots.jsonl`` +
``metrics.prom`` + ``events.jsonl``), so every existing stdlib CLI
(``wf_slo.py`` / ``wf_health.py`` / ``wf_state.py`` / ``wf_top.py``) works
on the aggregator directory unchanged.

Subcommands:

- ``serve``    — run the aggregator until SIGINT/SIGTERM::

      python scripts/wf_fleet.py serve --listen tcp://0.0.0.0:9900 \\
          --out wf_fleet --specs specs.json
      # on every host:
      WF_MONITORING=1 WF_TELEMETRY=tcp://aggregator:9900 python my_run.py

- ``status``   — one-shot read of an aggregator (or any monitoring)
  directory: connected hosts, fleet counters, per-SLO states.
- ``selftest`` — one-shot agent→aggregator loopback on an ephemeral
  endpoint (synthetic snapshots, no JAX, no network beyond loopback):
  proves the wire framing + aggregation + artifact schema end to end.
  CI runs this under a poisoned-JAX PYTHONPATH.

Stdlib only (``observability/{journal,device_health,slo,fleet}.py`` are
loaded by file path — the ``wf_state.py`` convention), so the aggregator
runs on any box, without JAX installed.

Exit codes: 0 = served/rendered/selftest passed, 2 = missing/unreadable
inputs, bad endpoint, or a failed selftest (``tests/test_fleet.py`` pins
the contract).
"""

import argparse
import importlib.util
import json
import os
import signal
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs(names=("journal", "device_health", "slo", "fleet")):
    """Load the observability helper modules by file path under a synthetic
    package — no windflow_tpu package import, no JAX (the wf_slo.py
    loader, grown the fleet module)."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in names:
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return (sys.modules["wf_obs.device_health"], sys.modules["wf_obs.slo"],
            sys.modules["wf_obs.fleet"])


def _resolve_specs(slo_mod, specs_arg):
    """``--specs`` > ``WF_SLO`` env > None (fleet SLOs are opt-in on the
    aggregator: without a spec set it still merges + writes artifacts, it
    just never judges)."""
    if specs_arg:
        return slo_mod.resolve_specs(specs_arg)
    env = os.environ.get("WF_SLO", "")
    if env not in ("", "0"):
        return slo_mod.resolve_specs(env)
    return None


# ------------------------------------------------------------ serve


def cmd_serve(args) -> int:
    dh, slo_mod, fleet = _load_obs()
    try:
        fleet.parse_endpoint(args.listen)
    except ValueError as e:
        print(f"wf_fleet: bad --listen endpoint: {e}", file=sys.stderr)
        return 2
    try:
        specs = _resolve_specs(slo_mod, args.specs)
    except (OSError, ValueError, TypeError) as e:
        print(f"wf_fleet: cannot resolve the SLO spec set: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    agg = fleet.FleetAggregator(
        args.listen, args.out, specs=specs, max_skew_s=args.max_skew,
        cooldown_s=args.cooldown, max_incidents=args.max_incidents,
        snapshot_keep=args.snapshot_keep)
    try:
        agg.start()
    except OSError as e:
        print(f"wf_fleet: cannot listen on {args.listen!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    stop = []
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.append(1))
    print(f"wf_fleet: serving on {agg.endpoint} -> {args.out!r} "
          f"({len(specs) if specs else 0} fleet SLO spec(s); "
          f"point hosts at WF_TELEMETRY={agg.endpoint})", flush=True)
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        agg.stop()
        print(f"wf_fleet: stopped — {agg.stats()['ticks']} fleet tick(s) "
              f"from {agg.stats()['hosts_seen']} host(s)", flush=True)
    return 0


# ------------------------------------------------------------ status


def cmd_status(args) -> int:
    dh, slo_mod, fleet = _load_obs()
    try:
        snap, series = dh.load_snapshots(args.monitoring_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"wf_fleet: cannot load snapshots from "
              f"{args.monitoring_dir!r}: {type(e).__name__}: {e}\n"
              f"(point --monitoring-dir at a wf_fleet serve --out "
              f"directory)", file=sys.stderr)
        return 2
    fl = snap.get("fleet") or {}
    if args.json:
        print(json.dumps({
            "monitoring_dir": args.monitoring_dir,
            "fleet": fl,
            "hosts": snap.get("hosts") or [],
            "merged_from": snap.get("merged_from"),
            "schema_mismatch": snap.get("schema_mismatch"),
            "slo": snap.get("slo") or {},
            "snapshots": len(series),
        }, indent=1, sort_keys=True))
        return 0
    print(f"wf_fleet: {args.monitoring_dir!r} — "
          f"{fl.get('hosts_connected', 0)}/{fl.get('hosts_seen', 0)} "
          f"host(s) connected, {fl.get('ticks', len(series))} fleet "
          f"tick(s), {fl.get('frames_received', 0)} frame(s) "
          f"({fl.get('frames_torn', 0)} torn)")
    if snap.get("schema_mismatch"):
        print(f"wf_fleet: MIXED-SCHEMA fleet — per-host snapshot schema "
              f"versions differ: "
              f"{json.dumps(snap['schema_mismatch'], sort_keys=True)}")
    for h in snap.get("hosts") or []:
        conn = ("" if "connected" not in h else
                ("  [LIVE]" if h["connected"] else "  [GONE]"))
        mon = f"  mon_dir={h['mon_dir']}" if h.get("mon_dir") else ""
        print(f"  host {h.get('host', '?'):<12} "
              f"graph={h.get('graph', '?')}{mon}{conn}")
    slo = snap.get("slo") or {}
    for name in sorted(slo):
        row = slo[name]
        print(f"  slo  {name:<16} state={row.get('state', '?'):<5} "
              f"burn_fast={row.get('burn_fast', 0):g} "
              f"burn_slow={row.get('burn_slow', 0):g} "
              f"pages={row.get('pages', 0)}")
    return 0


# ------------------------------------------------------------ selftest


def _synthetic_snap(host: str, tick: int) -> dict:
    """A minimal-but-schema-complete Reporter snapshot (the shape
    ``MetricsRegistry.snapshot`` emits) for the loopback selftest."""
    return {
        "graph": "selftest", "schema": 1, "wall_time": time.time(),
        "uptime_s": float(tick), "ticks": tick,
        "operators": [
            {"name": "src", "role": "source", "outputs": 32 * (tick + 1),
             "inputs": 0, "drops": 0, "service_time_us": {"p50": 10.0},
             "service_samples": tick + 1},
            {"name": "map", "role": "map", "outputs": 32 * (tick + 1),
             "inputs": 32 * (tick + 1), "drops": 0,
             "service_time_us": {"p50": 20.0}, "service_samples": tick + 1},
        ],
        "totals": {"outputs": 32 * (tick + 1), "drops": 0},
        "e2e_latency_us": {"p50": 100.0, "p95": 150.0, "p99": 200.0,
                           "samples": tick + 1},
        "queues": {"src->map": 1 + (tick % 2)},
        "ordering": {}, "recovery": {}, "control": {"counters": {}},
    }


def cmd_selftest(args) -> int:
    import tempfile
    dh, slo_mod, fleet = _load_obs()
    out = args.out or tempfile.mkdtemp(prefix="wf_fleet_selftest_")
    agg = fleet.FleetAggregator("127.0.0.1:0", out, max_skew_s=0.2)
    agg.start()
    hosts = ("host0", "host1")
    agents = [fleet.TelemetryAgent(agg.endpoint, host=h, outbox=8)
              for h in hosts]
    failures = []
    try:
        for a in agents:
            a.start()
        for tick in range(args.ticks):
            for h, a in zip(hosts, agents):
                a.offer(_synthetic_snap(h, tick))
            time.sleep(0.05)
        # the aggregator emits on round-complete; give the last round a
        # beat to land before tearing the agents down
        deadline = time.monotonic() + 5.0
        while (agg.stats()["frames_received"] < args.ticks * len(hosts)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        for a in agents:
            st = a.stats()
            if st["frames_dropped"]:
                failures.append(f"agent dropped {st['frames_dropped']} "
                                f"frame(s) against a live aggregator")
            if st["frames_sent"] != args.ticks:
                failures.append(f"agent sent {st['frames_sent']} != "
                                f"{args.ticks} frames")
    finally:
        for a in agents:
            a.close()
        agg.stop()
    try:
        snap, series = dh.load_snapshots(out)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        failures.append(f"aggregator artifacts unreadable: "
                        f"{type(e).__name__}: {e}")
        snap, series = {}, []
    if snap:
        if snap.get("merged_from") != len(hosts):
            failures.append(f"merged_from={snap.get('merged_from')} != "
                            f"{len(hosts)}")
        if not snap.get("fleet", {}).get("ticks"):
            failures.append("no fleet ticks recorded in snapshot.json")
        # the merged view must stay CLI-compatible: totals summed across
        # hosts, queues MAX-folded, e2e latency present
        want = len(hosts) * 32 * args.ticks
        got = (snap.get("totals") or {}).get("outputs")
        if got != want:
            failures.append(f"merged totals.outputs={got} != {want}")
    ev = [e.get("event") for e in dh.load_journal(out)]
    if "fleet_host_join" not in ev:
        failures.append("no fleet_host_join journal event")
    if not os.path.exists(os.path.join(out, "metrics.prom")):
        failures.append("metrics.prom missing")
    if failures:
        print("wf_fleet selftest: FAIL\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 2
    print(f"wf_fleet selftest: OK — {len(series)} fleet tick(s) from "
          f"{len(hosts)} loopback host(s) -> {out!r}")
    return 0


# ------------------------------------------------------------ main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_fleet",
        description="windflow_tpu fleet telemetry aggregator (serve / "
                    "status / selftest)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sv = sub.add_parser("serve", help="run the aggregator daemon")
    sv.add_argument("--listen", default="tcp://127.0.0.1:9900",
                    help="endpoint to accept host streams on "
                         "(tcp://HOST:PORT or unix:///path.sock; "
                         "port 0 = ephemeral)")
    sv.add_argument("--out", default="wf_fleet",
                    help="aggregator output directory (Reporter schema: "
                         "snapshot.json + snapshots.jsonl + metrics.prom "
                         "+ events.jsonl + incidents/)")
    sv.add_argument("--specs", default=None, metavar="JSON",
                    help="fleet SLO spec set (JSON file path or inline "
                         "JSON; default WF_SLO env, else no fleet SLOs)")
    sv.add_argument("--max-skew", type=float, default=1.0,
                    help="straggler timeout: emit a partial fleet tick "
                         "if a round stays incomplete this long (s)")
    sv.add_argument("--cooldown", type=float, default=60.0,
                    help="fleet incident capture cooldown (s)")
    sv.add_argument("--max-incidents", type=int, default=8,
                    help="retained fleet incident bundles")
    sv.add_argument("--snapshot-keep", type=int, default=None,
                    help="keep-last-N retention for the fleet "
                         "snapshots.jsonl (default unlimited)")
    sv.set_defaults(fn=cmd_serve)

    st = sub.add_parser("status", help="one-shot aggregator dir summary")
    st.add_argument("--monitoring-dir", default="wf_fleet",
                    help="aggregator output directory to read")
    st.add_argument("--json", action="store_true",
                    help="machine-readable output")
    st.set_defaults(fn=cmd_status)

    se = sub.add_parser("selftest",
                        help="one-shot agent->aggregator loopback proof")
    se.add_argument("--out", default=None,
                    help="write the loopback aggregator artifacts here "
                         "(default: a fresh temp dir)")
    se.add_argument("--ticks", type=int, default=5,
                    help="synthetic Reporter ticks per loopback host")
    se.set_defaults(fn=cmd_selftest)

    args = ap.parse_args(argv)
    try:
        _load_obs()
    except (OSError, ImportError, SyntaxError) as e:
        print(f"wf_fleet: cannot load observability helpers from "
              f"{REPO!r}: {type(e).__name__}: {e}\n"
              f"(keep scripts/wf_fleet.py next to its windflow_tpu tree — "
              f"it reuses the telemetry plane by file path)",
              file=sys.stderr)
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
