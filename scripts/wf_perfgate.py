#!/usr/bin/env python3
"""wf_perfgate — the hermetic perf gate over this repository.

Compiles the gate workloads (YSB + mp-matrix chains) AOT on the CPU backend,
reads XLA's logical cost model (FLOPs / bytes accessed per step), and
compares against the checked-in ratchet-down baseline
(``windflow_tpu/analysis/perfgate_baseline.json``); CPU-proxy kernel
microbenchmarks ride along as advisory trend rows. Zero device access — the
whole gate runs on a laptop or a CI box with the tunnel down.

    JAX_PLATFORMS=cpu python scripts/wf_perfgate.py            # text report
    python scripts/wf_perfgate.py --format=json                # machine-readable
    python scripts/wf_perfgate.py --update-baseline            # bank current costs

Exit codes (the wf_lint.py contract): 0 = clean, 1 = findings (regressions,
stale pins, unpinned workloads), 2 = internal error / explicit-but-missing
baseline — a broken gate must never masquerade as a clean one.

Baseline override: ``--baseline`` or the ``WF_PERFGATE_BASELINE`` env var.
``--update-baseline`` rewrites the resolved baseline from the current
measurement (do this ONLY for intentional cost changes; the ratchet exists
so improvements are banked and regressions cannot hide behind old pins).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_perfgate",
        description="windflow_tpu hermetic perf gate (XLA cost-analysis "
                    "pins + CPU-proxy microbenchmarks, no device)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline file overriding analysis/"
                         "perfgate_baseline.json (WF_PERFGATE_BASELINE env "
                         "does the same)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current measurement "
                         "and exit 0")
    ap.add_argument("--rtol", type=float, default=None,
                    help="relative tolerance around each cost pin "
                         "(default 0.02)")
    ap.add_argument("--skip-proxy", action="store_true",
                    help="skip the CPU-proxy microbenchmarks (cost pins "
                         "only)")
    ap.add_argument("--strict-proxy", action="store_true",
                    help="fail on proxy timings beyond the advisory factor "
                         "(noisy boxes: leave off)")
    ap.add_argument("--reps", type=int, default=3,
                    help="proxy microbenchmark repetitions (min taken)")
    args = ap.parse_args(argv)

    try:
        sys.path.insert(0, REPO)
        # the gate is hermetic BY CONSTRUCTION: pin the CPU backend before
        # jax initializes so a configured TPU tunnel (JAX_PLATFORMS=axon on
        # the dev box) can neither be touched nor hang the gate — an
        # unconditional overwrite, NOT setdefault
        os.environ["JAX_PLATFORMS"] = "cpu"
        from windflow_tpu.analysis import perfgate

        if args.baseline:
            # resolve against the INVOKER's cwd (the wf_lint.py convention)
            os.environ["WF_PERFGATE_BASELINE"] = \
                os.path.abspath(args.baseline)
        bpath = perfgate.baseline_path(REPO)
        if args.update_baseline:
            report = perfgate.measure(skip_proxy=args.skip_proxy,
                                      reps=args.reps)
            perfgate.save_baseline(bpath, report)
            print(f"wf_perfgate: pinned {len(report['workloads'])} "
                  f"workload(s) to {bpath}")
            return 0
        report, findings = perfgate.run_gate(
            REPO, rtol=(args.rtol if args.rtol is not None
                        else perfgate.DEFAULT_RTOL),
            skip_proxy=args.skip_proxy, strict_proxy=args.strict_proxy,
            reps=args.reps)
    except Exception as e:  # noqa: BLE001 — a broken gate must exit 2,
        #                     never masquerade as clean (0) or dirty (1)
        print(f"wf_perfgate: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({"report": report, "findings": findings}, indent=1))
    else:
        for w, row in sorted(report["workloads"].items()):
            print(f"{w}@{row['capacity']}: flops={row['flops']:.6g} "
                  f"bytes={row['bytes_accessed']:.6g}")
        for k, row in sorted(report.get("proxy", {}).items()):
            print(f"proxy {k}: {row['ns_per_elem']:g} ns/elem "
                  f"({row['elems']} elems)")
        for x in findings:
            print(f"FINDING [{x['kind']}] {x['message']}")
        print(f"wf_perfgate: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
