#!/usr/bin/env python3
"""wf_progcheck — the device-program analyzer (WF3xx) over this repository.

Traces the closed jaxprs of every registered audit target's step/scan
programs (``windflow_tpu/analysis/progcheck.py`` — zero FLOPs, zero device)
and gates on the WF300-WF305 findings:

    python scripts/wf_progcheck.py                    # the whole audit set
    python scripts/wf_progcheck.py --targets nexmark  # one family
    python scripts/wf_progcheck.py --format=json      # machine-readable
    python scripts/wf_progcheck.py --select WF30x     # family filter
    python scripts/wf_progcheck.py --explain WF305    # what a code means
    python scripts/wf_progcheck.py --update-baseline  # accept, keep rationales
    python scripts/wf_progcheck.py --fingerprints     # per-program hashes

``--select``/``--ignore``/``--explain`` share the wf_lint conventions
(comma-separated codes, a trailing ``x`` matches a family). Exit codes: 0 =
clean, 1 = findings (INCLUDING baseline entries without a written rationale
— a suppression is an argued decision, the WF26x discipline), 2 = broken
invocation or internal error. Unlike every other wf_* CLI this one NEEDS
JAX (program analysis traces real jaxprs); on a box without it, exit 2
with a one-line explanation, never a traceback.

Baseline: ``windflow_tpu/analysis/progcheck_baseline.json`` (override with
``--baseline`` / ``WF_PROGCHECK_BASELINE``). ``--update-baseline`` rewrites
it from the current findings, PRESERVING rationales already written for
entries that still match; new entries get ``"rationale": ""`` for a human
to fill — the gate stays red until they do.
"""

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _jax_missing() -> str:
    """Empty string when jax imports; else the reason (checked BEFORE the
    package import so a jax-less box gets a verdict, not a traceback)."""
    try:
        import jax  # noqa: F401
        return ""
    except Exception as e:  # noqa: BLE001 — any import failure = no jax
        return f"{type(e).__name__}: {e}"


def _load():
    """Package imports (progcheck traces real operator code, so the full
    ``windflow_tpu`` package — and therefore JAX — must be importable)."""
    sys.path.insert(0, REPO)
    from windflow_tpu.analysis import lint, progcheck
    return lint, progcheck


def _parse_codes(rules, text: str):
    """wf_lint's token grammar, verbatim semantics: trailing ``x`` =
    family by prefix, exact tokens must be registered — a typo must break
    the invocation (exit 2), never silently select nothing."""
    import re
    codes = set()
    for tok in [t.strip() for t in text.split(",") if t.strip()]:
        if re.fullmatch(r"WF\d+x", tok):
            fam = [c for c in rules if c.startswith(tok[:-1])]
            if not fam:
                raise ValueError(f"unknown rule family {tok!r}")
            codes.update(fam)
        elif tok in rules:
            codes.add(tok)
        else:
            raise ValueError(
                f"unknown rule code {tok!r} (see --explain, or the RULES "
                f"table in windflow_tpu/analysis/lint.py)")
    return codes


def _explain(code: str) -> int:
    """RULES row + the progcheck docstring block — via lint.py loaded BY
    FILE PATH, so --explain works even on a box without JAX."""
    path = os.path.join(REPO, "windflow_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("wf_analysis_lint", path)
    lint = importlib.util.module_from_spec(spec)
    sys.modules["wf_analysis_lint"] = lint
    spec.loader.exec_module(lint)
    if code not in lint.RULES:
        print(f"wf_progcheck: unknown rule code {code!r}; registered: "
              f"{', '.join(sorted(lint.RULES))}", file=sys.stderr)
        return 2
    severity, summary = lint.RULES[code]
    print(f"{code} [{severity}] {summary}")
    doc = lint.progcheck_doc() if code.startswith("WF30") else \
        (lint.__doc__ or "")
    in_block = False
    for line in doc.splitlines():
        if line.strip().startswith(code):
            in_block = True
        elif in_block and (line.strip().startswith("WF")
                           or line.strip().startswith("=====")):
            break
        if in_block:
            print(line)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_progcheck",
        description="windflow_tpu device-program analyzer (WF3xx)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--root", default=REPO,
                    help="repository root (default: this repo)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file overriding analysis/"
                         "progcheck_baseline.json (WF_PROGCHECK_BASELINE "
                         "env does the same)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(rationales already written are preserved; new "
                         "entries get an empty rationale to fill) and "
                         "exit 0")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--select", default=None, metavar="CODES",
                    help="comma-separated codes/families to run in "
                         "isolation (WF305 or WF30x)")
    ap.add_argument("--ignore", default=None, metavar="CODES",
                    help="comma-separated codes/families to drop")
    ap.add_argument("--explain", default=None, metavar="WFnnn",
                    help="print what a rule code means and exit")
    ap.add_argument("--targets", default=None, metavar="NAMES",
                    help="comma-separated audit-target families to trace "
                         "(default: all registered; see "
                         "progcheck.AUDIT_TARGETS)")
    ap.add_argument("--fingerprints", action="store_true",
                    help="also print each traced program's canonical "
                         "structural fingerprint")
    args = ap.parse_args(argv)

    if args.explain:
        # docstring-only path: must work WITHOUT jax (wf_lint convention)
        try:
            return _explain(args.explain)
        except Exception as e:  # noqa: BLE001 — broken invocation = 2
            print(f"wf_progcheck: internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2

    missing = _jax_missing()
    if missing:
        print("wf_progcheck: JAX is not importable on this box — program "
              "analysis traces real jaxprs and cannot run without it "
              f"({missing})", file=sys.stderr)
        return 2

    try:
        lint, pc = _load()
        if args.update_baseline and (args.select or args.ignore):
            print("wf_progcheck: refusing --update-baseline with "
                  "--select/--ignore (a partial baseline would drop the "
                  "other codes' suppressions)", file=sys.stderr)
            return 2
        keep = _parse_codes(lint.RULES, args.select) if args.select else None
        drop = _parse_codes(lint.RULES, args.ignore) if args.ignore else None
        targets = ([t.strip() for t in args.targets.split(",") if t.strip()]
                   if args.targets else None)
        if args.baseline:
            os.environ["WF_PROGCHECK_BASELINE"] = \
                os.path.abspath(args.baseline)

        programs = []
        for name in (targets or sorted(pc.AUDIT_TARGETS)):
            if name not in pc.AUDIT_TARGETS:
                raise ValueError(
                    f"unknown audit target {name!r}; registered: "
                    f"{', '.join(sorted(pc.AUDIT_TARGETS))}")
            programs += pc.AUDIT_TARGETS[name]()
        findings = pc.analyze_programs(programs)
        if keep is not None:
            findings = [x for x in findings if x.code in keep]
        if drop is not None:
            findings = [x for x in findings if x.code not in drop]
        bpath = pc.baseline_path(args.root)
        if args.update_baseline:
            pc.save_baseline(bpath, findings)
            empty = sum(1 for e in json.load(open(bpath))["findings"]
                        if not e["rationale"].strip())
            print(f"wf_progcheck: wrote {len(findings)} finding(s) to "
                  f"{bpath}"
                  + (f" — {empty} without a rationale: fill them or the "
                     f"gate stays red" if empty else ""))
            return 0
        if args.no_baseline:
            fresh, suppressed, problems = findings, [], []
        else:
            counts, problems = pc.load_baseline(bpath)
            fresh = pc.apply_baseline(findings, counts)
            fresh_ids = {id(x) for x in fresh}
            suppressed = [x for x in findings if id(x) not in fresh_ids]
        fps = ([{"target": p.target, "kind": p.kind, "k": p.k,
                 "shards": p.shards, "capacity": p.capacity,
                 "fingerprint": pc.program_fingerprint(p.closed)}
                for p in programs] if args.fingerprints else None)
    except Exception as e:  # noqa: BLE001 — a broken analyzer must exit 2,
        #                     never masquerade as a clean (0) or dirty (1) run
        print(f"wf_progcheck: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({
            "findings": [x.to_dict() for x in fresh],
            "suppressed": len(suppressed),
            "baseline_problems": problems,
            "programs": len(programs),
            **({"fingerprints": fps} if fps is not None else {}),
        }, indent=1))
    else:
        if fps is not None:
            for row in fps:
                print(f"{row['target']}/{row['kind']} k={row['k']} "
                      f"shards={row['shards']} cap={row['capacity']}  "
                      f"{row['fingerprint']}")
        for x in fresh:
            print(x.render())
        for p in problems:
            print(f"wf_progcheck: baseline entry WITHOUT a rationale: {p} "
                  f"— a suppression is an argued decision; write one")
        print(f"wf_progcheck: {len(fresh)} finding(s) "
              f"({len(suppressed)} baselined, {len(programs)} programs"
              + (f", {len(problems)} baseline entries missing a rationale"
                 if problems else "") + ")")
    return 1 if (fresh or problems) else 0


if __name__ == "__main__":
    sys.exit(main())
