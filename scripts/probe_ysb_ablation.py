"""Per-prefix YSB ablation in the EXACT bench_ysb configuration (same source,
ops, pane ring, donation, async timing loop) — reproduces the BASELINE.md
device-time decomposition table with one fresh process per prefix (the r03
measurement-integrity rule; run via a shell loop or scripts/run_ablation.sh).

Usage: python scripts/probe_ysb_ablation.py <n_ops> [batch]
  n_ops 0..4: source only, +filter, +join, +rekey, +window
Prints one line: ABLATE <n_ops> <ms_per_step>. WF_DUMP_HLO=1 additionally
writes the optimized HLO to scripts/hlo_ablate_<n_ops>.txt.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("WF_CPU"):           # smoke-test escape hatch (dead tunnel)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from windflow_tpu.benchmarks import ysb
from windflow_tpu.runtime.pipeline import CompiledChain

BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 20
STEPS = 30


def run(n_ops: int) -> float:
    panes_per_batch = BATCH // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN) + 1
    src = ysb.make_source(total=(3 * STEPS + 2) * BATCH)
    ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                       max_wins=panes_per_batch + 64)[:n_ops]
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=BATCH)

    def step(states, start):
        batch = src.make_batch(jnp.asarray(start, jnp.int32), BATCH)
        states = list(states)
        for j, op in enumerate(chain.ops):
            states[j], batch = op.apply(states[j], batch)
        # reduce to a scalar so every prefix returns the same tiny output
        # (a full-batch D2H would swamp the tunnel and distort the compare)
        tot = jnp.sum(batch.valid.astype(jnp.int32))
        if "cmp" in batch.payload:
            tot = tot + jnp.sum(jnp.where(batch.valid, batch.payload["cmp"], 0))
        return tuple(states), tot

    step = jax.jit(step, donate_argnums=0)
    if os.environ.get("WF_DUMP_HLO"):
        import bench
        specs = bench._arg_specs((tuple(chain.states), 0))
        txt = step.lower(*specs).compile().as_text()
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"hlo_ablate_{n_ops}.txt")
        with open(path, "w") as f:
            f.write(txt)

    states, out = step(tuple(chain.states), 0)
    jax.block_until_ready(out)
    times = []
    pos = 1
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            states, out = step(states, pos * BATCH)
            pos += 1
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return sorted(times)[1] / STEPS


if __name__ == "__main__":
    n = int(sys.argv[1])
    dt = run(n)
    print(f"ABLATE {n} {dt * 1e3:.4f} ms/step (batch={BATCH})")
