"""Parse scripts/ablation.log + scripts/join_probes.log into one decision
table: per-prefix marginals, impl A/B deltas, and the join-variant ranking.
Run after the probe watcher completes; prints markdown to stdout.

Usage: python scripts/summarize_probes.py [--latest-only]
(--latest-only keeps only rows after the last '===' run header in each log.)
"""

import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
PREFIX_NAMES = ["source gen", "+ filter", "+ join", "+ rekey", "+ window"]


def _read(path, latest_only):
    try:
        with open(os.path.join(HERE, path)) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    if latest_only:
        for i in range(len(lines) - 1, -1, -1):
            if lines[i].startswith("==="):
                return lines[i:]
    return lines


def parse_ablation(latest_only):
    """Returns (base_rows{n: ms}, variant_rows[(label, n, ms)]) for the LAST
    run in the log: every '===' header resets all state, so rows from earlier
    runs (possibly at a different batch size, appended by run_ablation.sh's
    '>>') can never mix into one table, and a labeled probe that died without
    printing its ABLATE line cannot leak its label onto the next run's base."""
    base, variants = {}, []
    label = None
    for ln in _read("ablation.log", latest_only):
        if ln.startswith("==="):
            base, variants, label = {}, [], None
            continue
        m = re.match(r"--- (.+) prefix (\d+)", ln)
        if m:
            label = m.group(1)
            continue
        m = re.match(r"ABLATE (\d+) ([0-9.]+) ms/step", ln)
        if m:
            n, ms = int(m.group(1)), float(m.group(2))
            if label is None:
                base[n] = ms
            else:
                variants.append((label, n, ms))
                label = None
    return base, variants


def parse_joins(latest_only):
    out = {}
    for ln in _read("join_probes.log", latest_only):
        if ln.startswith("==="):
            out = {}                      # last run only — never mix runs
            continue
        m = re.match(r"PROBE (\S+) ([0-9.]+) ms/step", ln)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def main():
    latest = "--latest-only" in sys.argv
    base, variants = parse_ablation(latest)
    joins = parse_joins(latest)

    if base:
        print("## YSB per-prefix ablation (ms/step)\n")
        print("| prefix | ms | marginal |")
        print("|---|---|---|")
        prev = 0.0
        for n in sorted(base):
            name = PREFIX_NAMES[n] if n < len(PREFIX_NAMES) else f"prefix {n}"
            print(f"| {name} | {base[n]:.3f} | {base[n] - prev:+.3f} |")
            prev = base[n]
        print()
    if variants:
        print("## Impl A/B (full-chain / prefix rows, ms/step)\n")
        print("| config | prefix | ms | vs XLA base |")
        print("|---|---|---|---|")
        for label, n, ms in variants:
            b = base.get(n)
            delta = f"{ms - b:+.3f}" if b is not None else "?"
            print(f"| {label} | {n} | {ms:.3f} | {delta} |")
        print()
    if joins:
        print("## Join variants (ms/step)\n")
        print("| probe | ms |")
        print("|---|---|")
        for k, v in sorted(joins.items(), key=lambda kv: kv[1]):
            print(f"| {k} | {v:.3f} |")
        std = {k[len("standalone_"):]: v for k, v in joins.items()
               if k.startswith("standalone_")}
        pre = {k[len("prefix2_"):]: v for k, v in joins.items()
               if k.startswith("prefix2_")}
        b = pre.get("base")
        if b is not None and pre:
            print("\nper-variant IN-CHAIN marginal over prefix2_base:")
            for k, v in sorted(pre.items(), key=lambda kv: kv[1]):
                if k != "base":
                    s = std.get(k)
                    s_txt = f", standalone {s:.3f}" if s is not None else ""
                    print(f"  {k}: {v - b:+.3f} ms{s_txt}")
    if not (base or variants or joins):
        print("no probe rows found (run the watcher first)")


if __name__ == "__main__":
    main()
