#!/usr/bin/env python3
"""wf_profile — profile-on-page inspection + bounded live-capture CLI.

Summarizes the device-profiler evidence a monitoring run committed
(``WF_PROFILE=1`` — ``observability/profiling.py``) and joins it against
the snapshot's device-time attribution:

- the **profile ledger**: every committed incident bundle under
  ``<dir>/incidents/`` with its ``profile.json`` — captured (file list +
  bytes), skipped (the recorded reason: session guard held, jax
  unavailable, max captures), or absent (a pre-profile bundle);
- the **device-time table**: the snapshot's per-stage ``health.device_time``
  rows (device ms vs host dispatch ms vs ``dispatch_ratio``) with every
  stage at or past the dispatch-bound threshold flagged as a
  ``[FUSION CANDIDATE]`` — the cross-reference that turns a raw capture
  into "this stage's time is launch overhead, fuse it" (the
  ``wf_health.py`` classifier, rendered next to the capture that proves it
  on-device).

**Live capture**: ``--capture LOGDIR [--window-ms N]`` opens one bounded
window through the ONE ``stats.xprof_trace`` session guard right now —
this path needs an importable ``jax`` (and the real ``windflow_tpu``
package) and exits 2 without one; a held session surfaces the guard's
RuntimeError naming the holder.

Produce the inputs with::

    WF_MONITORING=1 WF_SLO=1 WF_PROFILE=1 WF_SERVE=1 python my_service.py
    python scripts/wf_profile.py --monitoring-dir wf_monitoring

Summary mode is stdlib only (``observability/profiling.py`` +
``device_health.py`` + ``slo.py`` are loaded by file path — the
``wf_slo.py`` convention), so it works on any box the artifacts were
copied to, without JAX installed.

Exit codes: 0 = summary rendered (or capture succeeded), 2 =
missing/unreadable inputs, capture impossible (no jax / guard held), or
usage error (``scripts/ci.sh`` pins the poisoned-jax capture path).
"""

import argparse
import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs(names=("journal", "device_health", "slo", "profiling")):
    """Load the observability helper modules by file path under a synthetic
    package — no windflow_tpu package import, no JAX (the wf_slo.py
    loader, grown the profiling module)."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in names:
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return (sys.modules["wf_obs.device_health"], sys.modules["wf_obs.slo"],
            sys.modules["wf_obs.profiling"])


# ------------------------------------------------------------ report pieces


def profile_rows(prof_mod, slo_mod, mon_dir):
    """One row per committed bundle: (bundle name, manifest, profile dict
    or None)."""
    bundles, torn = slo_mod.list_incidents(mon_dir)
    rows = []
    for man in bundles:
        rows.append((os.path.basename(man["path"]), man,
                     prof_mod.load_profile(man["path"])))
    return rows, torn


def ledger_section(rows, torn):
    lines = ["== profile ledger (committed incident bundles) =="]
    if not rows and not torn:
        lines.append("  (no incident bundles captured — enable with "
                     "WF_MONITORING=1 WF_SLO=1 WF_PROFILE=1)")
        return lines
    for name, man, prof in rows:
        head = f"  {name:<40} slo={man.get('slo')} tick={man.get('tick')}"
        if prof is None:
            lines.append(head + "  profile: ABSENT (bundle predates "
                                "WF_PROFILE or profile.json unreadable)")
        elif "profile_skipped" in prof:
            lines.append(head
                         + f"  profile: SKIPPED ({prof['profile_skipped']})")
        else:
            files = prof.get("files", [])
            total = sum(int(f.get("bytes", 0)) for f in files)
            lines.append(head + f"  profile: captured "
                                f"window={prof.get('window_ms', 0):g} ms "
                                f"files={len(files)} bytes={total}")
            for f in files[:8]:
                lines.append(f"      {f.get('name')}  ({f.get('bytes')} B)")
            if len(files) > 8:
                lines.append(f"      ... {len(files) - 8} more file(s)")
    for name in torn:
        lines.append(f"  {name:<40} TORN (no committed manifest — crash "
                     f"mid-capture)")
    return lines


def device_time_section(dh, snap):
    """Per-stage device-time attribution out of the latest snapshot, with
    the dispatch-bound classifier's fusion candidates flagged inline."""
    lines = ["== device-time attribution (snapshot health.device_time) =="]
    health = snap.get("health") or {}
    dt = health.get("device_time") or {}
    if not dt:
        lines.append("  (no device-time rows — enable the health ledger "
                     "with WF_MONITORING_HEALTH=1 so captures have "
                     "per-stage rows to land on)")
        return lines
    thresh = float(getattr(dh, "DISPATCH_BOUND_RATIO", 0.5))
    lines.append(f"  {'stage':<28} {'device_ms':>10} {'dispatch_ms':>11} "
                 f"{'samples':>7} {'ratio':>6}")
    for label in sorted(dt):
        row = dt[label] or {}
        ratio = row.get("dispatch_ratio")
        flag = ""
        if isinstance(ratio, (int, float)) and ratio >= thresh:
            flag = "  [FUSION CANDIDATE]"
        lines.append(
            f"  {label:<28} {row.get('device_ms', 0):>10g} "
            f"{row.get('dispatch_ms', 0):>11g} {row.get('samples', 0):>7} "
            f"{(f'{ratio:g}' if isinstance(ratio, (int, float)) else '—'):>6}"
            f"{flag}")
    bound = health.get("dispatch_bound") or {}
    if bound:
        lines.append(f"  dispatch-bound (ratio >= {thresh:g} — host launch "
                     f"overhead rivals device work; fuse with K>1 "
                     f"dispatch): {', '.join(sorted(bound))}")
    return lines


def _capture(args) -> int:
    """One bounded live window through the ONE session guard — needs the
    real package (and jax); every failure mode is exit 2 with the reason."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)        # scripts/ is sys.path[0], not REPO
    try:
        from windflow_tpu.observability.profiling import profile_window
    except Exception as e:  # noqa: BLE001 — no jax / broken install
        print(f"wf_profile: cannot import windflow_tpu for a live capture: "
              f"{type(e).__name__}: {e}\n"
              f"(--capture opens a jax.profiler window — it needs an "
              f"importable jax; bundle summaries work without one)",
              file=sys.stderr)
        return 2
    try:
        summary = profile_window(args.capture, window_ms=args.window_ms)
    except RuntimeError as e:
        print(f"wf_profile: capture refused: {e}\n"
              f"(the ONE stats.xprof_trace session guard is held, or the "
              f"backend cannot profile — retry when the session closes)",
              file=sys.stderr)
        return 2
    except OSError as e:
        print(f"wf_profile: capture failed: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print(f"wf_profile: captured {summary['window_ms']:g} ms window "
              f"into {summary['logdir']!r} "
              f"({len(summary['files'])} file(s))")
        for f in summary["files"]:
            print(f"  {f['name']}  ({f['bytes']} B)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_profile",
        description="windflow_tpu profile-on-page CLI (incident-bundle "
                    "profile ledger + per-stage device-time attribution; "
                    "--capture opens one bounded live window)")
    ap.add_argument("--monitoring-dir", default="wf_monitoring",
                    help="monitoring output directory (incidents/ + "
                         "snapshot.json)")
    ap.add_argument("--bundle", default=None, metavar="DIR",
                    help="summarize one incident bundle's profile.json "
                         "instead of the whole ledger")
    ap.add_argument("--capture", default=None, metavar="LOGDIR",
                    help="open one bounded jax.profiler window into LOGDIR "
                         "right now (needs jax; exit 2 without it or when "
                         "the one xprof session guard is held)")
    ap.add_argument("--window-ms", type=float, default=None,
                    help="capture window for --capture (default: "
                         "WF_PROFILE_WINDOW_MS, else the built-in default)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    try:
        dh, slo_mod, prof_mod = _load_obs()
    except (OSError, ImportError, SyntaxError) as e:
        print(f"wf_profile: cannot load observability helpers from "
              f"{REPO!r}: {type(e).__name__}: {e}\n"
              f"(keep scripts/wf_profile.py next to its windflow_tpu tree — "
              f"it reuses the bundle/profile readers by file path)",
              file=sys.stderr)
        return 2

    if args.window_ms is None:
        env = os.environ.get("WF_PROFILE_WINDOW_MS", "")
        args.window_ms = float(env) if env else prof_mod.DEFAULT_WINDOW_MS
    if args.capture:
        return _capture(args)

    if args.bundle:
        prof = prof_mod.load_profile(args.bundle)
        if prof is None:
            print(f"wf_profile: no readable profile.json under "
                  f"{args.bundle!r}\n(a committed bundle carries either a "
                  f"capture summary or a profile_skipped reason once "
                  f"WF_PROFILE is on — this bundle has neither)",
                  file=sys.stderr)
            return 2
        print(json.dumps(prof, indent=1, sort_keys=True))
        return 0

    if not os.path.isdir(args.monitoring_dir):
        print(f"wf_profile: monitoring directory {args.monitoring_dir!r} "
              f"does not exist\n(run with WF_MONITORING=1 WF_SLO=1 "
              f"WF_PROFILE=1, or point --monitoring-dir / --bundle at "
              f"copied artifacts)", file=sys.stderr)
        return 2
    try:
        snap, _series = dh.load_snapshots(args.monitoring_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"wf_profile: cannot load snapshots from "
              f"{args.monitoring_dir!r}: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    rows, torn = profile_rows(prof_mod, slo_mod, args.monitoring_dir)

    if args.json:
        print(json.dumps({
            "monitoring_dir": args.monitoring_dir,
            "bundles": [{"bundle": name, "slo": man.get("slo"),
                         "tick": man.get("tick"), "profile": prof}
                        for name, man, prof in rows],
            "torn": torn,
            "device_time": (snap.get("health") or {}).get("device_time"),
            "dispatch_bound": (snap.get("health") or {}).get(
                "dispatch_bound"),
        }, indent=1, sort_keys=True, default=str))
        return 0

    captured = sum(1 for _n, _m, p in rows
                   if p is not None and "profile_skipped" not in p)
    print(f"wf_profile: {args.monitoring_dir!r} — {len(rows)} bundle(s), "
          f"{captured} with device captures")
    print()
    print("\n".join(ledger_section(rows, torn)))
    print()
    print("\n".join(device_time_section(dh, snap)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
