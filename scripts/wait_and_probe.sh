#!/bin/bash
# r05 probe watcher v2. The count-lift detection fix (commit 81f602a) is
# expected to collapse the YSB window stage (step ~8.1 -> ~3.1 ms), so the
# FIRST action on the next tunnel window is a fresh YSB headline capture —
# persisted immediately in case the window is short. Then the diagnosis
# probes (per-prefix ablation — whose runner also refreshes the isolated
# stateless row — then join variants), then the isolated keyed_cb refresh.
# Probe every 120s. Logs: scripts/tunnel_watch.log, scripts/ablation.log,
# scripts/join_probes.log.
cd /root/repo
LOG=scripts/tunnel_watch.log
echo "$(date -u +%FT%TZ) probe-watcher-v2 start" >> "$LOG"
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jax.device_put(jnp.ones((1024,), jnp.float32))
assert float((x*2).sum()) == 2048.0
print('probe ok:', d)
" >> "$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) TUNNEL UP — capturing post-fix YSB headline" >> "$LOG"
    break
  fi
  echo "$(date -u +%FT%TZ) probe failed/hung" >> "$LOG"
  sleep 120
done
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
timeout 1200 python -c "
import bench
tps, step, roof = bench.bench_ysb()
bench.record('ysb', {'tps': tps, 'step_s': step, 'batch': bench.BATCH,
                     'roofline': roof}, methodology='watcher-standalone')
bench.record_headline({'metric': 'YSB tuples/sec/chip', 'value': round(tps),
                       'unit': 'tuples/s',
                       'vs_baseline': round(tps / bench.BASELINE_TPS, 3)},
                      methodology='watcher-standalone')
print('YSB post-count-lift-fix:', tps / 1e6, 'M t/s,', step * 1e3, 'ms/step')
" > "scripts/capture_r05_ysb_postfix_$STAMP.log" 2>&1
rc=$?   # BEFORE any $(...) — a command substitution would clobber $?
echo "$(date -u +%FT%TZ) post-fix ysb done rc=$rc ($(tail -1 scripts/capture_r05_ysb_postfix_$STAMP.log))" >> "$LOG"
bash scripts/run_ablation.sh
rc=$?
echo "$(date -u +%FT%TZ) ablation done rc=$rc" >> "$LOG"
if [ "$rc" -eq 3 ]; then
  echo "$(date -u +%FT%TZ) tunnel died mid-ablation — watcher exiting (relaunch to retry)" >> "$LOG"
  exit 3
fi
bash scripts/run_join_probes.sh
rc=$?
echo "$(date -u +%FT%TZ) join probes done rc=$rc" >> "$LOG"
if [ "$rc" -eq 3 ]; then
  echo "$(date -u +%FT%TZ) tunnel died mid-join-probes — watcher exiting (relaunch to retry)" >> "$LOG"
  exit 3
fi
timeout 900 python -c "
import bench
r = bench._run_isolated('bench_keyed_cb()')
bench.record('keyed_cb', {'tps': r[0], 'step_s': r[1], 'roofline': r[2]},
             methodology='isolated-subprocess')
print('keyed_cb refreshed', r[0]/1e6)
" >> "$LOG" 2>&1
echo "$(date -u +%FT%TZ) probe-watcher-v2 done" >> "$LOG"
