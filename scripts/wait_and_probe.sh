#!/bin/bash
# r05 probe watcher: the YSB headline is already captured fresh this round
# (bench_captures/last_good.json, 2026-07-31T03:48Z). What the next tunnel
# window is FOR is diagnosis: the per-prefix ablation and the join-variant
# probes that decide the next perf fix. Probe every 120s; on first success run
# ablation -> join probes -> keyed_cb refresh (for the roofline overcount
# annotation). Logs: scripts/tunnel_watch.log, scripts/ablation.log,
# scripts/join_probes.log.
cd /root/repo
LOG=scripts/tunnel_watch.log
echo "$(date -u +%FT%TZ) probe-watcher start" >> "$LOG"
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jax.device_put(jnp.ones((1024,), jnp.float32))
assert float((x*2).sum()) == 2048.0
print('probe ok:', d)
" >> "$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) TUNNEL UP — running r05 probes" >> "$LOG"
    break
  fi
  echo "$(date -u +%FT%TZ) probe failed/hung" >> "$LOG"
  sleep 120
done
bash scripts/run_ablation.sh
echo "$(date -u +%FT%TZ) ablation done" >> "$LOG"
bash scripts/run_join_probes.sh
echo "$(date -u +%FT%TZ) join probes done" >> "$LOG"
timeout 900 python -c "
import bench
r = bench._run_isolated('bench_keyed_cb()')
bench.record('keyed_cb', {'tps': r[0], 'step_s': r[1], 'roofline': r[2]},
             methodology='isolated-subprocess')
print('keyed_cb refreshed', r[0]/1e6)
" >> "$LOG" 2>&1
echo "$(date -u +%FT%TZ) probe-watcher done" >> "$LOG"
