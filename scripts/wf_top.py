#!/usr/bin/env python3
"""wf_top — live terminal dashboard over a monitoring directory.

``top`` for a windflow_tpu run (or a whole fleet): polls the Reporter's
atomic artifacts (``snapshot.json`` + ``snapshots.jsonl``) and redraws a
one-screen view every ``--interval`` seconds:

- **stages** — per-operator throughput (live rates the registry computed,
  else a series delta), service-time p50/p99, drops;
- **queues** — ring depth vs capacity with a bar gauge ([FULL] at the
  watermark — the backpressure point at a glance);
- **event time** — the min-watermark frontier (who holds the graph back)
  and per-edge watermark skew, when the run recorded them;
- **shards** — per-shard occupancy with the [HOT] marker (fleet merges
  host-tag the keys, so the view names WHICH host's shard);
- **SLOs** — per-SLO OK/WARN/PAGE with fast/slow burn and a burn trend
  sparkline over the recent ticks;
- **serving** — the serving front door (``windflow_tpu/serving``): live
  graph + hot-swap counters, socket framing health, and one row per
  tenant (admit/shed counters, bucket rate, worst tenant-labelled SLO
  state — a paging tenant is flagged on the line naming its shed rate);
- **remediation** — actuator setpoint gauges (admission tps, governor
  watermarks, tiered hot_capacity and its recommended value) + the
  self-driving engine's last-action ledger, when the run had
  ``remediation=``/``WF_REMEDIATION`` on;
- **HBM** — per-device headroom, when the health ledger is on;
- **fleet** — hosts connected / frames / torn-frame counters, when the
  directory is a ``wf_fleet.py serve`` aggregator output.

Point it at any monitoring dir — a single host's, or a fleet aggregator's
(the aggregator writes the exact Reporter schema, so everything renders
unchanged)::

    python scripts/wf_top.py --monitoring-dir wf_monitoring
    python scripts/wf_top.py --monitoring-dir wf_fleet --interval 0.5

``--once`` renders a single frame without clearing the screen (the CI
mode). Stdlib only (``observability/device_health.py`` is loaded by file
path — the ``wf_state.py`` convention): works on any box the artifacts
were copied to, without JAX installed.

Exit codes: 0 = rendered (or interrupted with ctrl-C), 2 =
missing/unreadable inputs (``tests/test_fleet.py`` pins the contract).
"""

import argparse
import importlib.util
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_STATE = {0: "ok", 1: "warn", 2: "page"}
_SPARK = "_.-~^"                      # burn sparkline ramp (low -> high)


def _load_obs(names=("journal", "device_health", "slo")):
    """Load the observability helper modules by file path under a synthetic
    package — no windflow_tpu package import, no JAX (the wf_slo.py
    loader)."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in names:
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return sys.modules["wf_obs.device_health"]


def _fmt_bytes(n):
    if n is None:
        return "—"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def _bar(frac, width=12):
    frac = max(0.0, min(1.0, frac))
    full = int(round(frac * width))
    return "#" * full + "." * (width - full)


def _spark(values, lo=0.0, hi=None):
    """A tiny ASCII sparkline (portable: no unicode blocks)."""
    if not values:
        return ""
    hi = hi if hi is not None else max(values) or 1.0
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        idx = int((max(lo, min(hi, v)) - lo) / span * (len(_SPARK) - 1))
        out.append(_SPARK[idx])
    return "".join(out)


# ------------------------------------------------------------ panels


def header(snap, series, mon_dir):
    lines = [f"wf_top — {mon_dir!r}  graph={snap.get('graph', '?')!r}  "
             f"uptime={snap.get('uptime_s', 0):.1f}s  "
             f"snapshots={len(series)}  "
             f"{time.strftime('%H:%M:%S', time.localtime())}"]
    fl = snap.get("fleet")
    if fl:
        lines.append(
            f"fleet: {fl.get('hosts_connected', 0)}/"
            f"{fl.get('hosts_seen', 0)} host(s) connected  "
            f"ticks={fl.get('ticks', 0)}  "
            f"frames={fl.get('frames_received', 0)} "
            f"({fl.get('frames_torn', 0)} torn)")
    if snap.get("hosts"):
        lines.append("hosts: " + "  ".join(
            f"{h.get('host', '?')}"
            + ("" if "connected" not in h
               else ("[LIVE]" if h["connected"] else "[GONE]"))
            for h in snap["hosts"]))
    if snap.get("schema_mismatch"):
        lines.append(f"MIXED-SCHEMA fleet: "
                     f"{json.dumps(snap['schema_mismatch'], sort_keys=True)}")
    tel = snap.get("telemetry")
    if tel:
        lines.append(
            f"telemetry: {'up' if tel.get('connected') else 'DOWN'}  "
            f"sent={tel.get('frames_sent', 0)}  "
            f"dropped={tel.get('frames_dropped', 0)}  "
            f"outbox={tel.get('outbox_depth', 0)}")
    return lines


def _series_rate(series, name, field="outputs_sent"):
    """tuples/s from the last two snapshots carrying the operator —
    the fallback when the registry didn't compute live rates."""
    pts = []
    for s in series[-2:]:
        wall = s.get("wall_time")
        for row in s.get("operators") or []:
            if isinstance(row, dict) and row.get("name") == name:
                pts.append((wall, row.get(field)))
    if len(pts) == 2 and None not in pts[0] and None not in pts[1]:
        dt = pts[1][0] - pts[0][0]
        if dt > 0:
            return (pts[1][1] - pts[0][1]) / dt
    return None


def stages_panel(snap, series):
    lines = ["== stages =="]
    ops = [r for r in (snap.get("operators") or []) if isinstance(r, dict)]
    if not ops:
        lines.append("  (no operator rows yet)")
        return lines
    lines.append(f"  {'operator':<18} {'in tps':>10} {'out tps':>10} "
                 f"{'batches/s':>10} {'svc p50':>9} {'svc p99':>9} "
                 f"{'drops':>7}")
    for row in ops:
        name = str(row.get("name", "?"))
        tin = row.get("rate_in_tps")
        tout = row.get("rate_out_tps")
        if not tout:
            tout = _series_rate(series, name) or tout
        bps = row.get("rate_batches_in_per_s")
        svc = row.get("service_time_us") or {}
        drops = (row.get("tuples_dropped_old", 0) or 0) + \
            (row.get("drops", 0) or 0)
        hosts = row.get("hosts")
        tag = f" ({len(hosts)} hosts)" if hosts else ""
        lines.append(
            f"  {name + tag:<18} "
            f"{(f'{tin:,.0f}' if tin else '—'):>10} "
            f"{(f'{tout:,.0f}' if tout else '—'):>10} "
            f"{(f'{bps:,.1f}' if bps else '—'):>10} "
            f"{svc.get('p50', 0):>8.0f}u {svc.get('p99', 0):>8.0f}u "
            f"{drops:>7}")
    e2e = snap.get("e2e_latency_us") or {}
    if e2e:
        lines.append(f"  e2e latency: p50={e2e.get('p50', 0):.0f}us  "
                     f"p95={e2e.get('p95', 0):.0f}us  "
                     f"p99={e2e.get('p99', 0):.0f}us")
    return lines


def queues_panel(snap):
    lines = ["== queues =="]
    queues = snap.get("queues") or {}
    if not queues:
        lines.append("  (no ring gauges — threaded/pipegraph drivers "
                     "publish these)")
        return lines
    caps = snap.get("queue_capacity") or {}
    for edge in sorted(queues):
        depth = queues[edge]
        cap = caps.get(edge)
        if cap:
            frac = depth / cap
            flag = "  [FULL]" if depth >= cap else ""
            lines.append(f"  {edge:<24} {depth:>4}/{cap:<4} "
                         f"[{_bar(frac)}]{flag}")
        else:
            lines.append(f"  {edge:<24} {depth:>4}")
    return lines


def event_time_panel(snap):
    et = snap.get("event_time") or {}
    if not et:
        return None
    lines = ["== event time =="]
    if et.get("min_watermark_ts") is not None:
        front = et.get("frontier_operator")
        lines.append(f"  min watermark: {et['min_watermark_ts']}"
                     + (f"  (frontier: {front})" if front else ""))
    for edge, skew in sorted((et.get("edge_skew_ts") or {}).items()):
        lines.append(f"  skew {edge:<22} {skew:+}")
    return lines


def shards_panel(snap):
    shards = snap.get("shards") or {}
    if not shards:
        return None
    lines = ["== shards =="]
    hot = max(shards, key=lambda k: shards[k].get("occupancy_tuples", 0))
    peak = max((r.get("occupancy_tuples", 0) for r in shards.values()),
               default=0) or 1
    for k in sorted(shards, key=lambda x: (len(x), x)):
        r = shards[k]
        occ = r.get("occupancy_tuples", 0)
        flag = "  [HOT]" if k == hot and len(shards) > 1 else ""
        lines.append(f"  {k:<14} tuples={occ:<8} "
                     f"[{_bar(occ / peak)}] restarts={r.get('restarts', 0)}"
                     f"{flag}")
    return lines


def slo_panel(snap, series):
    slo = snap.get("slo") or {}
    if not slo:
        return None
    lines = ["== SLOs =="]
    lines.append(f"  {'slo':<16} {'state':<6} {'signal':>10} "
                 f"{'burn_fast':>9} {'burn_slow':>9} {'pages':>5}  trend")
    for name in sorted(slo):
        row = slo[name]
        if not isinstance(row, dict):
            continue
        state = row.get("state") or _STATE.get(row.get("code"), "?")
        flag = {"page": "  [PAGE]", "warn": "  [WARN]"}.get(state, "")
        hist = [(s.get("slo") or {}).get(name, {}).get("burn_fast", 0.0)
                for s in series[-24:]]
        v = row.get("signal")
        lines.append(
            f"  {name:<16} {state:<6} "
            f"{(f'{v:g}' if v is not None else '—'):>10} "
            f"{row.get('burn_fast', 0):>9g} {row.get('burn_slow', 0):>9g} "
            f"{row.get('pages', 0):>5}  {_spark(hist)}{flag}")
    if snap.get("slo_error"):
        lines.append(f"  SLO ENGINE DEGRADED: {snap['slo_error']}")
    return lines


def serving_panel(snap, series):
    """The serving front door at a glance: which graph is live (and how
    many hot-swaps got it there), the socket framing health, and one row
    per tenant — admit/shed counters, the bucket's current rate (the knob
    tenant_rate remediation turns), the e2e latency p99 + its exemplar
    trace id (``wf_trace.py --batch`` follows it; ``[SLOW]`` when the
    windowed p99 runs >= 2x the lifetime p99), and that tenant's worst
    SLO state (joined on the per-SLO rows' ``tenant`` label, so a paging
    tenant is flagged on the same line as its shed counters)."""
    srv = snap.get("serving") or {}
    if not srv:
        return None
    lines = ["== serving =="]
    lines.append(
        f"  graph={srv.get('graph', '?')}  "
        f"swaps={srv.get('swaps_applied', 0)} "
        f"(+{srv.get('swaps_rejected', 0)} rejected)"
        + (f"  endpoint={srv['endpoint']}" if srv.get("endpoint") else "")
        + (f"  clients={srv['clients_seen']:g}"
           if srv.get("clients_seen") is not None else ""))
    if srv.get("frames_decoded") is not None:
        lines.append(
            f"  frames: {srv.get('frames_decoded', 0):g} decoded  "
            f"{srv.get('frames_torn', 0):g} torn  "
            f"{srv.get('frames_dup', 0):g} dup"
            + (f"  (+{srv['unknown_offered']:g} from unknown tenants)"
               if srv.get("unknown_offered") else ""))
    tenants = srv.get("tenants") or {}
    if tenants:
        # worst SLO state per tenant, from the per-SLO rows' tenant label
        worst = {}
        for name, row in (snap.get("slo") or {}).items():
            if not isinstance(row, dict) or row.get("tenant") is None:
                continue
            code = row.get("code", 0) or 0
            t = row["tenant"]
            if code >= worst.get(t, (-1, ""))[0]:
                worst[t] = (code, name)
        # windowed p99 vs the cumulative one: a tenant whose last-tick p99
        # runs >= 2x its lifetime p99 is slow RIGHT NOW — flag it even
        # before the latency SLO's burn windows confirm
        lines.append(f"  {'tenant':<14} {'offered':>8} {'admitted':>9} "
                     f"{'shed':>6} {'tuples shed':>11} {'rate':>8} "
                     f"{'p99 ms':>8} {'exemplar':>10}  slo")
        for tid in sorted(tenants):
            row = tenants[tid]
            code, slo_name = worst.get(tid, (None, None))
            state = _STATE.get(code, "—") if code is not None else "—"
            flag = {"page": "  [PAGE]", "warn": "  [WARN]"}.get(state, "")
            rate = row.get("rate")
            p99 = row.get("e2e_p99_ms")
            p99t = row.get("e2e_p99_tick_ms")
            if isinstance(p99, (int, float)) and isinstance(
                    p99t, (int, float)) and p99 > 0 and p99t >= 2 * p99:
                flag = "  [SLOW]" + flag
            ex = row.get("e2e_p99_exemplar")
            lines.append(
                f"  {tid:<14} {row.get('offered', 0):>8g} "
                f"{row.get('admitted', 0):>9g} {row.get('shed', 0):>6g} "
                f"{row.get('shed_tuples', 0):>11g} "
                f"{(f'{rate:g}' if rate is not None else 'unlim'):>8} "
                f"{(f'{p99:g}' if isinstance(p99, (int, float)) else '—'):>8} "
                f"{(f'{int(ex):#x}' if isinstance(ex, int) else '—'):>10}  "
                f"{state}{f' ({slo_name})' if slo_name else ''}{flag}")
    return lines


def remediation_panel(snap):
    """The self-driving loop at a glance: actuator setpoint gauges (where
    the knobs currently sit) + the engine's last-action ledger."""
    rem = snap.get("remediation") or {}
    ctl = snap.get("control") or {}
    gauges = ctl.get("gauges") or {}
    counters = ctl.get("counters") or {}
    setpoints = [(lbl, gauges.get(g)) for lbl, g in (
        ("admission tps", "bucket_rate"),
        ("governor high", "governor_high_watermark"),
        ("governor low", "governor_low_watermark"),
        ("hot_capacity", "hot_capacity"),
        ("rec. hot_cap", "remediation_hot_capacity"),
        ("rec. delay", "remediation_recommended_delay"),
    ) if gauges.get(g) is not None]
    if not rem and not setpoints:
        return None
    lines = ["== remediation =="]
    if setpoints:
        lines.append("  setpoints: " + "  ".join(
            f"{lbl}={v:g}" for lbl, v in setpoints))
    if rem:
        lines.append(
            f"  engine: applied={rem.get('applied', 0)} "
            f"skipped={rem.get('skipped', 0)} "
            f"bound=[{', '.join(rem.get('bound', []) or []) or '—'}]"
            + (f"  (counters: actions="
               f"{counters.get('remediation_actions', 0):g} "
               f"skips={counters.get('remediation_skips', 0):g})"
               if counters.get("remediation_actions") is not None
               or counters.get("remediation_skips") is not None else ""))
        ledger = rem.get("ledger") or []
        for e in ledger[-6:]:          # the last-action ledger tail
            if e.get("applied"):
                detail = "  ".join(
                    f"{k}={e[k]:g}" if isinstance(e[k], (int, float))
                    else f"{k}={e[k]}"
                    for k in ("rate", "prev_rate", "recommended",
                              "new_shards", "pos") if e.get(k) is not None)
                lines.append(f"  APPLY {e.get('action', '?'):<18} "
                             f"{e.get('actuator', '?'):<16} "
                             f"slo={e.get('slo', '?')}  {detail}")
            else:
                lines.append(f"  skip  {e.get('action', '?'):<18} "
                             f"{e.get('actuator', '?'):<16} "
                             f"slo={e.get('slo', '?')}  "
                             f"reason={e.get('reason', '?')}")
    if snap.get("remediation_error"):
        lines.append(f"  REMEDIATION HOOK DEGRADED: "
                     f"{snap['remediation_error']}")
    return lines


def hbm_panel(snap):
    devices = (snap.get("health") or {}).get("devices") or []
    rows = [d for d in devices if d.get("headroom_bytes") is not None
            or d.get("bytes_in_use") is not None]
    if not rows:
        return None
    lines = ["== HBM =="]
    risky = set((snap.get("health") or {}).get("headroom_risk") or [])
    for d in rows:
        label = d.get("device", "?")
        flag = "  [LOW]" if label in risky else ""
        lines.append(f"  {label:<12} in_use={_fmt_bytes(d.get('bytes_in_use'))} "
                     f"headroom={_fmt_bytes(d.get('headroom_bytes'))}{flag}")
    return lines


def render(dh, mon_dir) -> str:
    snap, series = dh.load_snapshots(mon_dir)
    if not series:
        series = [snap]
    blocks = [header(snap, series, mon_dir), stages_panel(snap, series),
              queues_panel(snap)]
    for panel in (event_time_panel(snap), shards_panel(snap),
                  slo_panel(snap, series), serving_panel(snap, series),
                  remediation_panel(snap), hbm_panel(snap)):
        if panel:
            blocks.append(panel)
    return "\n\n".join("\n".join(b) for b in blocks)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_top",
        description="live terminal dashboard over a windflow_tpu "
                    "monitoring (or fleet aggregator) directory")
    ap.add_argument("--monitoring-dir", default="wf_monitoring",
                    help="monitoring output directory (a host's, or a "
                         "wf_fleet.py serve --out aggregator's)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="redraw period in seconds (default 1.0)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clear — "
                         "the CI/scripting mode)")
    args = ap.parse_args(argv)

    try:
        dh = _load_obs()
    except (OSError, ImportError, SyntaxError) as e:
        print(f"wf_top: cannot load observability helpers from {REPO!r}: "
              f"{type(e).__name__}: {e}\n"
              f"(keep scripts/wf_top.py next to its windflow_tpu tree — it "
              f"reuses the snapshot readers by file path)", file=sys.stderr)
        return 2

    if args.once:
        try:
            print(render(dh, args.monitoring_dir))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"wf_top: cannot load snapshots from "
                  f"{args.monitoring_dir!r}: {type(e).__name__}: {e}\n"
                  f"(run with WF_MONITORING=1, or point --monitoring-dir "
                  f"at a wf_fleet aggregator output)", file=sys.stderr)
            return 2
        return 0

    # live mode: the FIRST read must succeed (catch bad paths up front,
    # exit 2); after that, transient read races with the writer's atomic
    # replace just keep the previous frame for one interval
    try:
        frame = render(dh, args.monitoring_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"wf_top: cannot load snapshots from "
              f"{args.monitoring_dir!r}: {type(e).__name__}: {e}\n"
              f"(run with WF_MONITORING=1, or point --monitoring-dir at a "
              f"wf_fleet aggregator output)", file=sys.stderr)
        return 2
    try:
        while True:
            # ANSI home+clear-to-end keeps the redraw flicker-free on any
            # terminal; fall back gracefully when not a tty (plain append)
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J")
            print(frame, flush=True)
            time.sleep(max(0.05, args.interval))
            try:
                frame = render(dh, args.monitoring_dir)
            except (OSError, ValueError, json.JSONDecodeError):
                pass                 # keep last frame; writer mid-replace
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
