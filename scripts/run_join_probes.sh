#!/bin/bash
# One fresh process per probe (the r03 measurement-integrity rule); run on the
# real chip when the tunnel is up. Results append to scripts/join_probes.log.
# Exits 3 (via ok_or_bail) if the tunnel dies mid-run — callers must check.
cd /root/repo
LOG=scripts/join_probes.log
. scripts/tunnel_lib.sh
echo "=== $(date -u +%FT%TZ) batch=${1:-1048576}" >> "$LOG"
for p in prefix2_base prefix2_factored prefix2_factored_bf16 prefix2_take \
         prefix2_barrier prefix2_div prefix2_pallas_gather \
         prefix2_pallas_onehot standalone_factored \
         standalone_factored_bf16 standalone_take standalone_div \
         standalone_pallas_gather standalone_pallas_onehot; do
  dump=""
  case "$p" in prefix2_base|prefix2_factored|standalone_factored) dump="WF_DUMP_HLO=1";; esac
  env $dump timeout 900 python scripts/probe_join.py "$p" "${1:-1048576}" >> "$LOG" 2>&1
  ok_or_bail $? "$LOG"
done
tail -16 "$LOG"
