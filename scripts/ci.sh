#!/usr/bin/env bash
# ci.sh — the whole local gate in one command, one combined exit code:
#
#   wf_lint (framework-invariant linter, exit 0/1/2)
#     -> wf_perfgate (hermetic AOT cost pins + proxy microbenches, 0/1/2)
#     -> tier-1 tests (the ROADMAP.md verify command)
#
# Every step runs even when an earlier one failed (the full picture in one
# pass); the exit code is nonzero iff ANY step failed. Usage:
#
#   scripts/ci.sh              # everything
#   scripts/ci.sh --fast      # lint + perfgate only (seconds, no pytest)
set -u
cd "$(dirname "$0")/.."

overall=0
run_step() {
    local name="$1"; shift
    echo "==================== ${name} ===================="
    "$@"
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "ci: ${name} FAILED (rc=${rc})" >&2
        overall=1
    else
        echo "ci: ${name} ok"
    fi
}

run_step "wf_lint" python scripts/wf_lint.py
run_step "perf gate" env JAX_PLATFORMS=cpu python scripts/wf_perfgate.py

if [ "${1:-}" != "--fast" ]; then
    # the ROADMAP.md tier-1 verify command (minus the log plumbing)
    run_step "tier-1 tests" env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider
fi

if [ $overall -ne 0 ]; then
    echo "ci: FAILED" >&2
else
    echo "ci: all green"
fi
exit $overall
