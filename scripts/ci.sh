#!/usr/bin/env bash
# ci.sh — the whole local gate in one command, one combined exit code:
#
#   wf_lint (framework-invariant linter + WF26x concurrency pass, exit 0/1/2)
#     -> wf_perfgate (hermetic AOT cost pins + proxy microbenches, 0/1/2)
#     -> wf_progcheck (device-program analyzer, WF3xx jaxpr audit, 0/1/2)
#     -> tier-1 tests (the ROADMAP.md verify command)
#
# Every step runs even when an earlier one failed (the full picture in one
# pass); the exit code is nonzero iff ANY step failed.  A per-step duration
# summary prints at the end, and the wf_lint row carries its finding count
# (fresh + baselined) so a glance at the summary says whether the gate is
# clean or riding suppressions.  Usage:
#
#   scripts/ci.sh              # everything
#   scripts/ci.sh --fast      # lint + perfgate only (seconds, no pytest)
set -u
cd "$(dirname "$0")/.."

overall=0
step_names=()
step_rcs=()
step_secs=()
step_notes=()

run_step() {
    local name="$1"; shift
    echo "==================== ${name} ===================="
    local out; out=$(mktemp)
    local t0=$SECONDS
    "$@" 2>&1 | tee "$out"
    local rc=${PIPESTATUS[0]}
    local dt=$((SECONDS - t0))
    local note=""
    if [ "$name" = "wf_lint" ]; then
        # the one-line verdict ("wf_lint: N finding(s) (M baselined)")
        note=$(grep -a '^wf_lint:' "$out" | tail -1 | sed 's/^wf_lint: //')
    elif [ "$name" = "wf_progcheck" ]; then
        # "wf_progcheck: N finding(s) (M baselined, P programs)"
        note=$(grep -a '^wf_progcheck:' "$out" | tail -1 \
               | sed 's/^wf_progcheck: //')
    fi
    rm -f "$out"
    step_names+=("$name"); step_rcs+=("$rc")
    step_secs+=("$dt"); step_notes+=("$note")
    if [ "$rc" -ne 0 ]; then
        echo "ci: ${name} FAILED (rc=${rc})" >&2
        overall=1
    else
        echo "ci: ${name} ok${note:+ — ${note}}"
    fi
}

run_step "wf_lint" python scripts/wf_lint.py
run_step "perf gate" env JAX_PLATFORMS=cpu python scripts/wf_perfgate.py
# the device-program analyzer: jaxpr-level WF3xx audit over the registered
# target families (nexmark, ysb, mp-matrix, examples) — exits 1 on fresh
# findings OR baseline entries missing a written rationale
run_step "wf_progcheck" env JAX_PLATFORMS=cpu python scripts/wf_progcheck.py

# stdlib-CLI exit-code contracts under a poisoned-jax PYTHONPATH: every
# artifact CLI must run on a box without JAX (they load the observability
# helpers by file path), and wf_slo.py must additionally honor its
# 0 = ok / 1 = burning / 2 = unusable-inputs contract over a synthetic
# snapshots.jsonl.  Kept in one bash -c step so the temp tree and the
# poisoned jax module never leak into the later pytest step.
stdlib_cli_contracts() {
    local tmp rc
    tmp=$(mktemp -d) || return 1
    printf 'raise ImportError("stdlib CLIs must not import jax")\n' \
        > "$tmp/jax.py"
    # missing inputs -> exit 2, for every artifact CLI (wf_trace keys its
    # inputs off --trace-dir rather than --monitoring-dir; wf_fleet reads
    # through its status subcommand; wf_top needs --once or it would
    # block in the live redraw loop)
    local cli dirflag
    for cli in wf_slo wf_state wf_health wf_trace; do
        dirflag="--monitoring-dir"
        [ "$cli" = "wf_trace" ] && dirflag="--trace-dir"
        PYTHONPATH="$tmp" python "scripts/${cli}.py" \
            "$dirflag" "$tmp/nope" >/dev/null 2>&1
        rc=$?
        if [ "$rc" -ne 2 ]; then
            echo "ci: ${cli}.py missing-inputs contract broke (rc=${rc}," \
                 "want 2)" >&2
            rm -rf "$tmp"; return 1
        fi
    done
    PYTHONPATH="$tmp" python scripts/wf_fleet.py status \
        --monitoring-dir "$tmp/nope" >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 2 ]; then
        echo "ci: wf_fleet.py missing-inputs contract broke (rc=${rc}," \
             "want 2)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_top.py \
        --monitoring-dir "$tmp/nope" --once >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 2 ]; then
        echo "ci: wf_top.py missing-inputs contract broke (rc=${rc}," \
             "want 2)" >&2
        rm -rf "$tmp"; return 1
    fi
    # fleet loopback smoke: a one-shot agent->aggregator roundtrip on an
    # ephemeral endpoint (wf_fleet selftest), then the live dashboard and
    # the SLO CLI must both read the aggregator's Reporter-schema output
    # directory unchanged — all still without jax
    PYTHONPATH="$tmp" python scripts/wf_fleet.py selftest \
        --out "$tmp/fleet" >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_fleet.py selftest loopback broke (rc=${rc}, want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_top.py \
        --monitoring-dir "$tmp/fleet" --once >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_top.py on the aggregator dir broke (rc=${rc}," \
             "want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_slo.py \
        --monitoring-dir "$tmp/fleet" >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_slo.py on the aggregator dir broke (rc=${rc}," \
             "want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    # wf_slo burn contract: a series violating the latency target on every
    # tick must exit 1; a recovered tail must exit 0
    python - "$tmp" <<'PY'
import json, os, sys
tmp = sys.argv[1]
def snap(p99):
    return {"graph": "ci", "operators": [],
            "e2e_latency_us": {"p99": p99 * 1e3, "p99_tick": p99 * 1e3,
                               "samples": 8, "samples_tick": 8}}
burn = [snap(50.0) for _ in range(8)]
ok = burn + [snap(0.5) for _ in range(8)]
for name, series in (("burning", burn), ("recovered", ok)):
    d = os.path.join(tmp, name); os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "snapshots.jsonl"), "w") as f:
        for s in series:
            f.write(json.dumps(s) + "\n")
spec = [{"name": "lat", "signal": "e2e_p99_ms", "target": 10.0,
         "objective": 0.5, "fast_window": 2, "slow_window": 4}]
with open(os.path.join(tmp, "spec.json"), "w") as f:
    json.dump(spec, f)
# remediation ledger artifacts for the recovered dir: the engine section on
# the final snapshot + apply/skip journal events — wf_slo's remediation
# section must render them WITHOUT changing the 0/1 exit contract
rec = os.path.join(tmp, "recovered")
snaps = [json.loads(l) for l in open(os.path.join(rec, "snapshots.jsonl"))]
snaps[-1]["remediation"] = {
    "enabled": True, "applied": 1, "skipped": 1,
    "bound": ["admission_rate"], "actions": ["shed_harder"],
    "ledger": [{"action": "shed_harder", "actuator": "admission_rate",
                "slo": "lat", "burn": 2.0, "applied": True,
                "rate": 250.0, "prev_rate": 500.0}]}
with open(os.path.join(rec, "snapshots.jsonl"), "w") as f:
    for s in snaps:
        f.write(json.dumps(s) + "\n")
with open(os.path.join(rec, "events.jsonl"), "w") as f:
    f.write(json.dumps({"t": 1.0, "wall": 1.0,
                        "event": "remediation_apply",
                        "action": "shed_harder",
                        "actuator": "admission_rate", "slo": "lat",
                        "burn": 2.0, "applied": True, "rate": 250.0,
                        "prev_rate": 500.0}) + "\n")
    f.write(json.dumps({"t": 2.0, "wall": 2.0,
                        "event": "remediation_skip",
                        "action": "shed_harder",
                        "actuator": "admission_rate", "slo": "lat",
                        "burn": 1.9, "applied": False,
                        "reason": "damped"}) + "\n")
PY
    PYTHONPATH="$tmp" python scripts/wf_slo.py \
        --monitoring-dir "$tmp/burning" --specs "$tmp/spec.json" \
        >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 1 ]; then
        echo "ci: wf_slo.py burning contract broke (rc=${rc}, want 1)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_slo.py \
        --monitoring-dir "$tmp/recovered" --specs "$tmp/spec.json" \
        >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_slo.py recovered contract broke (rc=${rc}, want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    # remediation-section pins: the ledger renders (APPLY row + skip
    # reason), shows up in --json, and does NOT perturb the exit contract
    # (recovered stays 0) — still under the poisoned-jax PYTHONPATH
    local remout
    remout=$(PYTHONPATH="$tmp" python scripts/wf_slo.py \
        --monitoring-dir "$tmp/recovered" --specs "$tmp/spec.json" \
        --report remediation 2>&1)
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_slo.py remediation-section exit contract broke" \
             "(rc=${rc}, want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    if ! printf '%s' "$remout" | grep -q "APPLY" \
        || ! printf '%s' "$remout" | grep -q "reason=damped"; then
        echo "ci: wf_slo.py remediation section did not render the" \
             "apply/skip ledger" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_slo.py \
        --monitoring-dir "$tmp/recovered" --specs "$tmp/spec.json" --json \
        2>/dev/null | python -c '
import json, sys
d = json.load(sys.stdin)
rem = d["remediation"]
assert rem["recorded"]["applied"] == 1, rem
assert [e["event"] for e in rem["events"]] == \
    ["remediation_apply", "remediation_skip"], rem
'
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_slo.py --json remediation payload broke (rc=${rc})" >&2
        rm -rf "$tmp"; return 1
    fi
    # serving front-door contracts: the WFS1 loopback selftest (framing +
    # resync + per-tenant seq dedup, loaded by file path from
    # windflow_tpu/serving) and the missing-inputs status contract — still
    # without jax
    PYTHONPATH="$tmp" python scripts/wf_serve.py selftest >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_serve.py selftest loopback broke (rc=${rc}, want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_serve.py status \
        --monitoring-dir "$tmp/nope" >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 2 ]; then
        echo "ci: wf_serve.py missing-inputs contract broke (rc=${rc}," \
             "want 2)" >&2
        rm -rf "$tmp"; return 1
    fi
    # profile-on-page CLI contracts: summary mode is stdlib (missing inputs
    # -> 2), and --capture NEEDS jax so the poisoned box must get the
    # one-line exit-2 verdict, never a traceback and never a fake capture
    PYTHONPATH="$tmp" python scripts/wf_profile.py \
        --monitoring-dir "$tmp/nope" >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 2 ]; then
        echo "ci: wf_profile.py missing-inputs contract broke (rc=${rc}," \
             "want 2)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_profile.py \
        --capture "$tmp/prof" >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 2 ]; then
        echo "ci: wf_profile.py poisoned-jax --capture contract broke" \
             "(rc=${rc}, want 2)" >&2
        rm -rf "$tmp"; return 1
    fi
    # per-tenant wire-to-sink report pin: a synthetic flight recorder whose
    # ingest record carries the serving extras (tenant/seq/wire_ms/queue_ms)
    # must render the tenant section with the right slowest-segment verdict
    python - "$tmp" <<'PY'
import json, os, sys
d = os.path.join(sys.argv[1], "wiretrace"); os.makedirs(d, exist_ok=True)
with open(os.path.join(d, "meta.json"), "w") as f:
    json.dump({"run_id": "ci-wire", "capacity": 64, "dropped": 0}, f)
recs = [
    {"tid": 1, "stage": "source", "kind": "ingest", "t": 0.300, "pos": 0,
     "tenant": "noisy", "seq": 3, "wire_ms": 250.0, "queue_ms": 2.0},
    {"tid": 1, "stage": "chain", "kind": "begin", "t": 0.301},
    {"tid": 1, "stage": "chain", "kind": "end", "t": 0.304},
]
with open(os.path.join(d, "flight.jsonl"), "w") as f:
    for r in recs:
        f.write(json.dumps(r) + "\n")
PY
    local wireout
    wireout=$(PYTHONPATH="$tmp" python scripts/wf_trace.py \
        --trace-dir "$tmp/wiretrace" --report 2>&1)
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_trace.py per-tenant report exit contract broke" \
             "(rc=${rc}, want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    if ! printf '%s' "$wireout" \
            | grep -q "per-tenant wire-to-sink attribution" \
        || ! printf '%s' "$wireout" | grep -q "tenant 'noisy'" \
        || ! printf '%s' "$wireout" | grep -q "slowest segment: wire"; then
        echo "ci: wf_trace.py --report did not render the per-tenant" \
             "wire-to-sink section" >&2
        rm -rf "$tmp"; return 1
    fi
    # wf_progcheck is the ONE jax-needing CLI: on a box without jax it must
    # exit 2 with a one-line verdict (never a traceback), and its --explain
    # path (docstring-only, loaded by file path) must still work
    PYTHONPATH="$tmp" python scripts/wf_progcheck.py >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 2 ]; then
        echo "ci: wf_progcheck.py no-jax contract broke (rc=${rc}," \
             "want 2)" >&2
        rm -rf "$tmp"; return 1
    fi
    PYTHONPATH="$tmp" python scripts/wf_progcheck.py --explain WF300 \
        >/dev/null 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "ci: wf_progcheck.py --explain without jax broke (rc=${rc}," \
             "want 0)" >&2
        rm -rf "$tmp"; return 1
    fi
    rm -rf "$tmp"
    echo "stdlib CLI exit contracts ok (wf_slo 0/1/2 + remediation ledger,"
    echo "wf_state/wf_health/wf_trace/wf_fleet/wf_top/wf_serve/wf_profile 2"
    echo "on missing inputs, fleet + serving loopback selftests, wf_top/"
    echo "wf_slo over the aggregator dir, per-tenant wire-to-sink report;"
    echo "all without jax. wf_progcheck: 2 without jax, --explain still"
    echo "answers; wf_profile --capture: 2 without jax)"
}
run_step "stdlib CLIs" stdlib_cli_contracts

if [ "${1:-}" != "--fast" ]; then
    # the ROADMAP.md tier-1 verify command (minus the log plumbing)
    run_step "tier-1 tests" env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider
fi

echo "==================== summary ===================="
for i in "${!step_names[@]}"; do
    status=ok
    [ "${step_rcs[$i]}" -ne 0 ] && status="FAILED(rc=${step_rcs[$i]})"
    printf 'ci: %-14s %-14s %5ss%s\n' "${step_names[$i]}" "$status" \
        "${step_secs[$i]}" "${step_notes[$i]:+  ${step_notes[$i]}}"
done
if [ $overall -ne 0 ]; then
    echo "ci: FAILED" >&2
else
    echo "ci: all green"
fi
exit $overall
