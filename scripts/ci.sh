#!/usr/bin/env bash
# ci.sh — the whole local gate in one command, one combined exit code:
#
#   wf_lint (framework-invariant linter + WF26x concurrency pass, exit 0/1/2)
#     -> wf_perfgate (hermetic AOT cost pins + proxy microbenches, 0/1/2)
#     -> tier-1 tests (the ROADMAP.md verify command)
#
# Every step runs even when an earlier one failed (the full picture in one
# pass); the exit code is nonzero iff ANY step failed.  A per-step duration
# summary prints at the end, and the wf_lint row carries its finding count
# (fresh + baselined) so a glance at the summary says whether the gate is
# clean or riding suppressions.  Usage:
#
#   scripts/ci.sh              # everything
#   scripts/ci.sh --fast      # lint + perfgate only (seconds, no pytest)
set -u
cd "$(dirname "$0")/.."

overall=0
step_names=()
step_rcs=()
step_secs=()
step_notes=()

run_step() {
    local name="$1"; shift
    echo "==================== ${name} ===================="
    local out; out=$(mktemp)
    local t0=$SECONDS
    "$@" 2>&1 | tee "$out"
    local rc=${PIPESTATUS[0]}
    local dt=$((SECONDS - t0))
    local note=""
    if [ "$name" = "wf_lint" ]; then
        # the one-line verdict ("wf_lint: N finding(s) (M baselined)")
        note=$(grep -a '^wf_lint:' "$out" | tail -1 | sed 's/^wf_lint: //')
    fi
    rm -f "$out"
    step_names+=("$name"); step_rcs+=("$rc")
    step_secs+=("$dt"); step_notes+=("$note")
    if [ "$rc" -ne 0 ]; then
        echo "ci: ${name} FAILED (rc=${rc})" >&2
        overall=1
    else
        echo "ci: ${name} ok${note:+ — ${note}}"
    fi
}

run_step "wf_lint" python scripts/wf_lint.py
run_step "perf gate" env JAX_PLATFORMS=cpu python scripts/wf_perfgate.py

if [ "${1:-}" != "--fast" ]; then
    # the ROADMAP.md tier-1 verify command (minus the log plumbing)
    run_step "tier-1 tests" env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider
fi

echo "==================== summary ===================="
for i in "${!step_names[@]}"; do
    status=ok
    [ "${step_rcs[$i]}" -ne 0 ] && status="FAILED(rc=${step_rcs[$i]})"
    printf 'ci: %-14s %-14s %5ss%s\n' "${step_names[$i]}" "$status" \
        "${step_secs[$i]}" "${step_notes[$i]:+  ${step_notes[$i]}}"
done
if [ $overall -ne 0 ]; then
    echo "ci: FAILED" >&2
else
    echo "ci: all green"
fi
exit $overall
