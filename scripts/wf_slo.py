#!/usr/bin/env python3
"""wf_slo — SLO burn-rate / health-state / incident-forensics CLI.

Evaluates a declarative SLO spec set offline over any monitoring run's
``snapshots.jsonl`` (the exact burn/state math the live Reporter-tick engine
runs — ``observability/slo.py::evaluate_series``) and renders:

- the **burn-rate table**: per SLO, the latest signal value vs target, the
  fast/slow window burn rates, the health state, and the page count;
- the **state timeline**: every OK -> WARN -> PAGE -> OK transition with its
  tick — the incident's shape at a glance;
- the **incident ledger**: committed forensic bundles under
  ``<dir>/incidents/`` (triggering SLO, captured files, validation against
  each bundle's manifest), with manifest-less directories reported as TORN
  (a crash mid-capture — the manifest is the commit point, so a torn bundle
  never half-parses);
- any SLO sections the snapshots RECORDED live (the engine's own verdicts,
  when the run had ``slo=`` on);
- the **remediation ledger**: every ``remediation_apply`` /
  ``remediation_skip`` journal event joined against the burn table (which
  SLO the action served and what state that SLO ended the window in), plus
  the engine's recorded snapshot state — the self-driving loop's audit trail
  when the run had ``remediation=``/``WF_REMEDIATION`` on.

Spec source precedence: ``--specs`` (JSON file path or inline JSON) >
``WF_SLO`` env (same forms) > the built-in default spec set.

**Fleet mode**: ``--merge DIR [DIR...]`` folds N per-host monitoring
directories into one fleet series (``device_health.merge_monitoring_dirs``)
and evaluates the spec set over the MERGED view — the same burn math the
live fleet aggregator (``observability/fleet.py``) runs. A fleet
aggregator's own output directory is also a plain monitoring dir: point
``--monitoring-dir`` at it and everything (burn table, timeline, incident
ledger) renders unchanged.

Produce the inputs with::

    WF_MONITORING=1 WF_SLO=1 python my_run.py
    python scripts/wf_slo.py --monitoring-dir wf_monitoring

Stdlib only (``observability/slo.py`` + ``device_health.py`` + ``journal.py``
are loaded by file path — the ``wf_state.py`` convention), so this works on
any box the artifacts were copied to, without JAX installed.

Exit codes: 0 = no SLO burning (every final state OK), 1 = at least one SLO
burning in the evaluated window, 2 = missing/unreadable inputs or usage
error (``tests/test_slo.py`` pins the contract).
"""

import argparse
import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs(names=("journal", "device_health", "slo")):
    """Load the observability helper modules by file path under a synthetic
    package — no windflow_tpu package import, no JAX (the wf_health.py
    loader, grown the slo module)."""
    obs = os.path.join(REPO, "windflow_tpu", "observability")
    pkg = sys.modules.get("wf_obs")
    if pkg is None:
        pkg = types.ModuleType("wf_obs")
        pkg.__path__ = [obs]
        sys.modules["wf_obs"] = pkg
    for name in names:
        if f"wf_obs.{name}" in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            f"wf_obs.{name}", os.path.join(obs, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"wf_obs.{name}"] = mod
        spec.loader.exec_module(mod)
        setattr(pkg, name, mod)
    return sys.modules["wf_obs.device_health"], sys.modules["wf_obs.slo"]


# ------------------------------------------------------------ report pieces


def burn_table(report):
    lines = ["== SLO burn rates =="]
    if not report:
        lines.append("  (no SLOs evaluated)")
        return lines
    lines.append(f"  {'slo':<16} {'signal':<16} {'value':>12} {'target':>10} "
                 f"{'burn_fast':>9} {'burn_slow':>9} {'state':>6} "
                 f"{'pages':>5}")
    for name in sorted(report):
        row = report[name]
        v = row.get("signal")
        flag = ""
        if row.get("state") == "page":
            flag = "  [PAGE]"
        elif row.get("state") == "warn":
            flag = "  [WARN]"
        lines.append(
            f"  {name:<16} {row.get('signal_name', '?'):<16} "
            f"{(f'{v:g}' if v is not None else '—'):>12} "
            f"{row.get('target', 0):>10g} {row.get('burn_fast', 0):>9g} "
            f"{row.get('burn_slow', 0):>9g} {row.get('state', '?'):>6} "
            f"{row.get('pages', 0):>5}{flag}")
    return lines


def timeline(report):
    lines = ["== state timeline =="]
    any_tr = False
    for name in sorted(report):
        for tr in report[name].get("transitions", []):
            any_tr = True
            lines.append(f"  tick {tr['tick']:>5}  {name:<16} "
                         f"{tr['from']} -> {tr['to']}")
    if not any_tr:
        lines.append("  (no transitions — every SLO stayed OK over the "
                     "evaluated window)")
    return lines


def recorded_section(series):
    """The live engine's own verdicts, when the run recorded them."""
    last = next((s.get("slo") for s in reversed(series) if s.get("slo")),
                None)
    if not last:
        return None
    lines = ["== recorded live verdicts (snapshot 'slo' sections) =="]
    for name in sorted(last):
        row = last[name]
        lines.append(f"  {name:<16} state={row.get('state', '?'):<5} "
                     f"burn_fast={row.get('burn_fast', 0):g} "
                     f"burn_slow={row.get('burn_slow', 0):g} "
                     f"pages={row.get('pages', 0)}")
    return lines


def remediation_events(events):
    """The remediation ledger rows out of a journal event list (live
    Reporter-tick applies AND supervised commit-barrier applies share the
    two event names)."""
    return [e for e in events
            if e.get("event") in ("remediation_apply", "remediation_skip")]


def remediation_section(report, series, events):
    """Action timeline joined to the burn table: what the remediation layer
    did (or declined to do, and why) against each SLO's final state."""
    lines = ["== remediation =="]
    rows = remediation_events(events)
    recorded = next(
        (s.get("remediation") for s in reversed(series)
         if s.get("remediation")), None)
    if not rows and not recorded:
        lines.append("  (no remediation activity recorded — enable with "
                     "remediation=/WF_REMEDIATION=1 on a run with slo= on)")
        return lines
    if recorded:
        lines.append(
            f"  engine: applied={recorded.get('applied', 0)} "
            f"skipped={recorded.get('skipped', 0)} "
            f"bound=[{', '.join(recorded.get('bound', []) or []) or '—'}] "
            f"actions=[{', '.join(recorded.get('actions', []) or [])}]")
    if rows:
        lines.append(f"  {'event':<7} {'action':<18} {'actuator':<16} "
                     f"{'slo':<14} {'value':>8} {'slo end':>8}  detail")
        for e in rows:
            kind = "APPLY" if e.get("event") == "remediation_apply" \
                else "skip"
            # the burn-table join: the action's serving SLO and the state
            # that SLO ended the evaluated window in
            end = (report.get(e.get("slo"), {}) or {}).get("state", "—")
            v = e.get("burn", e.get("value"))
            detail = []
            if e.get("reason"):
                detail.append(f"reason={e['reason']}")
            if e.get("pos") is not None:
                detail.append(f"pos={e['pos']}")
            for k in ("rate", "prev_rate", "recommended", "new_shards"):
                if e.get(k) is not None:
                    detail.append(f"{k}={e[k]:g}" if isinstance(
                        e[k], (int, float)) else f"{k}={e[k]}")
            if e.get("host"):
                detail.append(f"host={e['host']}")
            lines.append(
                f"  {kind:<7} {e.get('action', '?'):<18} "
                f"{e.get('actuator', '?'):<16} {e.get('slo', '?'):<14} "
                f"{(f'{v:g}' if isinstance(v, (int, float)) else '—'):>8} "
                f"{end:>8}  {' '.join(detail)}")
    return lines


def incidents_section(slo_mod, mon_dir):
    lines = ["== incident bundles =="]
    bundles, torn = slo_mod.list_incidents(mon_dir)
    if not bundles and not torn:
        lines.append("  (none captured)")
        return lines
    for man in bundles:
        miss = (f"  MISSING: {', '.join(man['missing'])}"
                if man.get("missing") else "")
        lines.append(
            f"  {os.path.basename(man['path']):<40} slo={man.get('slo')} "
            f"tick={man.get('tick')} files={len(man.get('files', []))}"
            f"{miss}")
    for name in torn:
        lines.append(f"  {name:<40} TORN (no committed manifest — crash "
                     f"mid-capture)")
    return lines


def _resolve_specs(slo_mod, specs_arg):
    if specs_arg:
        return slo_mod.resolve_specs(specs_arg)
    env = os.environ.get("WF_SLO", "")
    if env not in ("", "0"):
        return slo_mod.resolve_specs(env)
    return slo_mod.default_specs()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_slo",
        description="windflow_tpu SLO CLI (burn-rate tables, state "
                    "timeline, incident bundles; exit 1 = burning)")
    ap.add_argument("--monitoring-dir", default="wf_monitoring",
                    help="monitoring output directory (snapshots.jsonl + "
                         "snapshot.json + events.jsonl + incidents/)")
    ap.add_argument("--merge", nargs="+", default=None, metavar="DIR",
                    help="merge N per-host monitoring directories (or "
                         "snapshots.jsonl paths) into one fleet series and "
                         "evaluate the spec set over the merged view "
                         "instead of reading --monitoring-dir")
    ap.add_argument("--specs", default=None, metavar="JSON",
                    help="SLO spec set: a JSON file path or inline JSON "
                         "(list of {name,signal,target,...}); default: "
                         "WF_SLO env, else the built-in default set")
    ap.add_argument("--report", choices=("all", "burn", "timeline",
                                         "incidents", "remediation"),
                    default="all",
                    help="which section(s) to render (default all)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: the evaluation report + "
                         "incident ledger + recorded live sections")
    args = ap.parse_args(argv)

    try:
        dh, slo_mod = _load_obs()
    except (OSError, ImportError, SyntaxError) as e:
        print(f"wf_slo: cannot load observability helpers from {REPO!r}: "
              f"{type(e).__name__}: {e}\n"
              f"(keep scripts/wf_slo.py next to its windflow_tpu tree — it "
              f"reuses the burn math and bundle readers by file path)",
              file=sys.stderr)
        return 2
    try:
        specs = _resolve_specs(slo_mod, args.specs)
    except (OSError, ValueError, TypeError) as e:
        print(f"wf_slo: cannot resolve the SLO spec set: "
              f"{type(e).__name__}: {e}\n"
              f"(--specs/WF_SLO take a JSON file path or inline JSON — a "
              f"list of spec objects or {{'specs': [...]}}; the validator "
              f"reports the same problems as WF116)", file=sys.stderr)
        return 2
    if not specs:
        # resolve_specs maps '[]'/'{"specs": []}' to an empty set — there
        # is nothing to evaluate, which is unusable input (2), NOT
        # "burning" (1): an automation caller must never read an empty
        # spec file as an active incident
        print("wf_slo: the resolved SLO spec set is empty — nothing to "
              "evaluate\n(--specs/WF_SLO need at least one "
              "{name,signal,target,...} object; omit both for the "
              "built-in default set)", file=sys.stderr)
        return 2
    problems = [f"{s.name}: {p}" for s in specs
                for p in slo_mod.spec_problems(s)]
    seen = set()
    for s in specs:
        # duplicate names are an engine-constructor error (the report keys
        # rows by name) — catch them HERE so a spec typo exits 2, never the
        # burning code 1
        if s.name in seen:
            problems.append(f"{s.name}: duplicate SLO name")
        seen.add(s.name)
    if problems:
        print("wf_slo: invalid SLO spec set (WF116):\n  "
              + "\n  ".join(problems), file=sys.stderr)
        return 2
    try:
        if args.merge:
            _latest, series, events = dh.merge_monitoring_dirs(args.merge)
        else:
            _latest, series = dh.load_snapshots(args.monitoring_dir)
            events = dh.load_journal(args.monitoring_dir)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        where = args.merge or args.monitoring_dir
        print(f"wf_slo: cannot load snapshots from "
              f"{where!r}: {type(e).__name__}: {e}\n"
              f"(run with WF_MONITORING=1 — add WF_SLO=1 for live "
              f"evaluation + incident capture)", file=sys.stderr)
        return 2
    if not series:
        series = [_latest]

    report = slo_mod.evaluate_series(specs, series)
    burning = slo_mod.burning(report)
    if args.merge:
        bundles, torn = [], []
    else:
        bundles, torn = slo_mod.list_incidents(args.monitoring_dir)
    # mixed-schema fleets are flagged, never silently folded
    # (device_health.merge_snapshots stamps schema_mismatch): surface the
    # per-host schema map so a reader knows the merged numbers span
    # incompatible snapshot generations
    mismatch = _latest.get("schema_mismatch") or next(
        (s.get("schema_mismatch") for s in reversed(series)
         if s.get("schema_mismatch")), None)

    if args.json:
        print(json.dumps({
            "monitoring_dir": (None if args.merge else args.monitoring_dir),
            "merged_dirs": args.merge,
            "schema_mismatch": mismatch,
            "snapshots": len(series),
            "specs": [{"name": s.name, "signal": s.signal,
                       "target": s.target, "objective": s.objective,
                       "fast_window": s.fast_window,
                       "slow_window": s.slow_window,
                       "warn_burn": s.warn_burn, "page_burn": s.page_burn,
                       "mode": s.resolved_mode()} for s in specs],
            "report": report,
            "burning": burning,
            "incidents": bundles,
            "incidents_torn": torn,
            "remediation": {
                "recorded": next(
                    (s.get("remediation") for s in reversed(series)
                     if s.get("remediation")), None),
                "events": remediation_events(events),
            },
        }, indent=1, sort_keys=True, default=str))
        return 1 if burning else 0

    head = (f"wf_slo: merged {_latest.get('merged_from')} host(s): "
            + ", ".join(h.get("host", "?")
                        for h in _latest.get("hosts", []))
            if args.merge else f"wf_slo: {args.monitoring_dir!r}")
    print(f"{head} — {len(series)} snapshot(s), "
          f"{len(specs)} SLO spec(s)"
          + (f", BURNING: {', '.join(burning)}" if burning
             else ", all OK"))
    if mismatch:
        print(f"wf_slo: MIXED-SCHEMA fleet — per-host snapshot schema "
              f"versions differ: {json.dumps(mismatch, sort_keys=True)} "
              f"(merged numbers span incompatible snapshot generations)")
    blocks = []
    if args.report in ("all", "burn"):
        blocks.append(burn_table(report))
    if args.report in ("all", "timeline"):
        blocks.append(timeline(report))
        rec = recorded_section(series)
        if args.report == "all" and rec:
            blocks.append(rec)
    if args.report in ("all", "remediation"):
        blocks.append(remediation_section(report, series, events))
    if args.report in ("all", "incidents"):
        if args.merge:
            if args.report == "incidents":
                blocks.append(
                    ["== incident bundles ==",
                     "  (not available in the --merge fleet view — "
                     "bundles live under each host's own "
                     "<monitoring_dir>/incidents/; a live fleet "
                     "aggregator correlates them into fleet bundles "
                     "under its own dir — point --monitoring-dir there)"])
        else:
            blocks.append(incidents_section(slo_mod, args.monitoring_dir))
    for b in blocks:
        print()
        print("\n".join(b))
    return 1 if burning else 0


if __name__ == "__main__":
    sys.exit(main())
